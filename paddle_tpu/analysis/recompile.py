"""Recompile-hazard detection: executable-cache signature monitoring.

``to_static`` hides a shape/dtype-keyed executable cache (jax.jit's
tracing cache — the reference's ConcreteProgram cache).  Every call with
a novel signature silently pays a full retrace+compile; the classic
sources are rank-varying inputs (pad-to-bucket forgotten), weak-type
flips (python scalar one call, 0-d array the next), and python scalars
riding positions that alternate between int and float.

This module is import-light on purpose (jit attaches a monitor to every
compiled callable): recording is OFF until switched on globally
(``PADDLE_TPU_ANALYZE`` env, ``enable_recompile_monitoring()``, or the
``monitor_recompiles()`` context manager) or per-callable
(``fn._signature_monitor.enabled = True``).
"""

from __future__ import annotations

import contextlib
import os
from typing import List

from paddle_tpu.analysis.diagnostics import Diagnostic, Severity

__all__ = ["SignatureMonitor", "enable_recompile_monitoring",
           "monitor_recompiles", "monitoring_enabled", "leaf_signature"]

_ENABLED = bool(os.environ.get("PADDLE_TPU_ANALYZE"))


def enable_recompile_monitoring(on: bool = True):
    global _ENABLED
    _ENABLED = on


def monitoring_enabled() -> bool:
    return _ENABLED


@contextlib.contextmanager
def monitor_recompiles():
    """Record signatures for every to_static callable inside the block."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = True
    try:
        yield
    finally:
        _ENABLED = prev


def leaf_signature(x):
    if hasattr(x, "_data"):
        x = x._data
    if isinstance(x, bool):
        return ("pyscalar", "bool")
    if isinstance(x, int):
        return ("pyscalar", "int")
    if isinstance(x, float):
        return ("pyscalar", "float")
    if isinstance(x, complex):
        return ("pyscalar", "complex")
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return ("array", tuple(x.shape), str(x.dtype),
                bool(getattr(x, "weak_type", False)))
    return ("static", type(x).__name__)


class SignatureMonitor:
    """Bounded per-callable log of call signatures, turned into
    Diagnostics by the recompile-hazard pass (or ``.report()``
    directly)."""

    def __init__(self, name: str = "<to_static>", max_records: int = 256,
                 cache_threshold: int = 8):
        self.name = name
        self.max_records = max_records
        self.cache_threshold = cache_threshold
        self.enabled = False          # per-callable override
        self.calls = 0
        self.records: List[tuple] = []   # unique signatures, call order
        self._seen = set()

    @property
    def active(self) -> bool:
        return self.enabled or _ENABLED

    def record(self, args, kwargs=None) -> bool:
        """Returns True when this call's signature is NOVEL (i.e. it
        would retrace) — the observability recompile counter feeds off
        this return value."""
        import jax
        self.calls += 1
        leaves = jax.tree.leaves(
            (args, kwargs or {}),
            is_leaf=lambda t: hasattr(t, "_data"))
        sig = tuple(leaf_signature(v) for v in leaves)
        if sig not in self._seen and len(self.records) < self.max_records:
            self._seen.add(sig)
            self.records.append(sig)
            return True
        return False

    def clear(self):
        self.calls = 0
        self.records = []
        self._seen = set()

    def report(self) -> List[Diagnostic]:
        diags: List[Diagnostic] = []
        n = len(self.records)
        if n == 0:
            return diags
        if n > self.cache_threshold:
            diags.append(Diagnostic(
                "recompile-hazard", Severity.WARNING,
                f"executable-cache churn on {self.name}: {n} distinct "
                f"call signatures across {self.calls} calls — each one "
                f"is a separate retrace + XLA compile",
                hint="pin shapes with input_spec / pad to buckets; keep "
                     "dtypes and scalar-vs-array choices stable"))

        width = max(len(s) for s in self.records)
        for pos in range(width):
            col = [s[pos] for s in self.records if pos < len(s)]
            kinds = {c[0] for c in col}
            if "pyscalar" in kinds and "array" in kinds:
                diags.append(Diagnostic(
                    "recompile-hazard", Severity.WARNING,
                    f"argument leaf {pos} of {self.name} alternates "
                    f"between python scalar and array (weak-type flip "
                    f"→ retrace)",
                    hint="convert once at the boundary: "
                         "jnp.asarray(x, dtype) on every call"))
                continue
            arrays = [c for c in col if c[0] == "array"]
            if len({len(c[1]) for c in arrays}) > 1:
                diags.append(Diagnostic(
                    "recompile-hazard", Severity.WARNING,
                    f"argument leaf {pos} of {self.name} varies in RANK "
                    f"across calls ({sorted({len(c[1]) for c in arrays})})"
                    f" — every rank is a separate executable",
                    hint="reshape/squeeze at the call boundary so the "
                         "compiled signature is stable"))
            if len({(c[2], c[3]) for c in arrays}) > 1 \
                    and len({c[2] for c in arrays}) == 1:
                diags.append(Diagnostic(
                    "recompile-hazard", Severity.WARNING,
                    f"argument leaf {pos} of {self.name} flips weak_type "
                    f"with identical shape/dtype — python-scalar capture "
                    f"forcing silent retraces",
                    hint="jnp.asarray with an explicit dtype makes the "
                         "leaf strongly typed on every call"))
            scalar_kinds = {c[1] for c in col if c[0] == "pyscalar"}
            if len(scalar_kinds) > 1:
                diags.append(Diagnostic(
                    "recompile-hazard", Severity.WARNING,
                    f"argument leaf {pos} of {self.name} is a python "
                    f"scalar of varying type ({sorted(scalar_kinds)})",
                    hint="normalize to one numeric type before the call"))
        return diags
