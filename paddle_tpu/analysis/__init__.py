"""paddle_tpu.analysis — jaxpr-level program linter, cost model and
sharding-consistency checker.

The reference framework's static-graph ProgramDesc enables whole-program
passes (validation, fusion planning, auto-parallel checks); our IR is
the jaxpr every ``to_static`` / ``TrainStep`` / predictor path already
produces.  This package traces any Layer / function / TrainStep
abstractly (no FLOPs run) and drives a pluggable pass pipeline over the
resulting ``ClosedJaxpr``, reporting structured ``Diagnostic``s.

    import paddle_tpu.analysis as analysis
    report = analysis.check(model, ids)           # runs all five passes
    print(report)
    report.extras["cost"].table()                 # FLOPs/bytes roll-up

Opt-in hooks (``analyze="warn"|"strict"`` kwargs, or the
``PADDLE_TPU_ANALYZE`` env var) live in ``jit.to_static``,
``jit.TrainStep``, ``inference.NativePredictor`` and
``inference.ContinuousBatchingEngine``; strict mode raises
``AnalysisError`` on ERROR-severity findings.  CLI:
``python -m paddle_tpu.analysis.lint module:symbol --spec int32[2,16]``.

Writing a custom pass: see paddle_tpu/analysis/README.md.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from paddle_tpu.analysis.diagnostics import (AnalysisError, AnalysisReport,
                                             Diagnostic, Severity, dedup)
from paddle_tpu.analysis.recompile import (SignatureMonitor,
                                           enable_recompile_monitoring,
                                           monitor_recompiles,
                                           monitoring_enabled)
from paddle_tpu.analysis.tracing import TraceResult, trace, walk_eqns
from paddle_tpu.analysis.passes import (DEFAULT_PASSES, PassContext,
                                        all_passes, get_pass, register_pass)

__all__ = [
    "check", "run_passes", "trace", "walk_eqns",
    "Diagnostic", "Severity", "AnalysisReport", "AnalysisError",
    "PassContext", "register_pass", "all_passes", "DEFAULT_PASSES",
    "SignatureMonitor", "enable_recompile_monitoring",
    "monitor_recompiles", "monitoring_enabled",
    "analysis_mode", "check_artifact",
]


def analysis_mode() -> Optional[str]:
    """Global opt-in from the environment: '' (off — default), 'warn'
    (run passes on hook points, print findings), 'strict' (raise
    AnalysisError on ERROR findings)."""
    v = os.environ.get("PADDLE_TPU_ANALYZE", "").strip().lower()
    if v in ("", "0", "off", "false"):
        return None
    return "strict" if v == "strict" else "warn"


def run_passes(tr: TraceResult, passes: Optional[List[str]] = None,
               options: Optional[Dict] = None) -> AnalysisReport:
    """Drive the pass pipeline over an existing trace."""
    report = AnalysisReport(target=tr.target_name)
    ctx = PassContext(trace=tr, options=dict(options or {}))
    for pass_id in (passes or DEFAULT_PASSES):
        fn = get_pass(pass_id)
        report.extend(fn(ctx))
        report.passes_run.append(pass_id)
    report.extras.update(ctx.extras)
    return report


def check(fn_or_layer, *example_args, passes: Optional[List[str]] = None,
          method: Optional[str] = None, param_specs: Optional[Dict] = None,
          mesh=None, options: Optional[Dict] = None, strict: bool = False,
          **example_kwargs) -> AnalysisReport:
    """Trace ``fn_or_layer`` with ``example_args`` and run the pass
    pipeline (all five built-ins by default).

    Accepts an ``nn.Layer`` (``method=`` selects e.g. ``"loss"``), a
    ``jit.TrainStep`` (pass one example batch), a ``to_static``-wrapped
    callable, or a plain function.  ``param_specs`` maps parameter names
    (or suffix patterns, as in ``LlamaForCausalLM.partition_specs``) to
    PartitionSpecs for the sharding pass; a TrainStep's placement and
    mpu layers' ``partition_spec`` annotations are picked up
    automatically.  ``strict=True`` raises ``AnalysisError`` when any
    ERROR-severity finding survives.
    """
    tr = trace(fn_or_layer, *example_args, method=method,
               param_specs=param_specs, mesh=mesh, **example_kwargs)
    report = run_passes(tr, passes=passes, options=options)
    if strict:
        report.raise_on_error()
    return report


def check_artifact(model_prefix: str, strict: bool = False):
    """Lint a ``jit.save`` artifact (see analysis/artifact.py)."""
    from paddle_tpu.analysis.artifact import check_artifact as _impl
    return _impl(model_prefix, strict=strict)
