"""paddle.save / paddle.load parity (reference: python/paddle/framework/io.py:646,888).

Serialization format: pickle of nested containers with Tensors converted to
numpy (same interchange idea as the reference's pickle-compatible state
dicts).  Sharded / async distributed checkpointing lives in
paddle_tpu.framework.checkpoint (orbax-backed)."""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from paddle_tpu.core.tensor import Parameter, Tensor

_MAGIC = b"PDTPU001"


def _to_storable(obj):
    if isinstance(obj, Parameter):
        return {"__paddle_tpu_param__": True, "data": np.asarray(obj._data),
                "trainable": obj.trainable, "name": obj.name}
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_storable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_storable(v) for v in obj)
    return obj


def _from_storable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_param__"):
            if return_numpy:
                return obj["data"]
            p = Parameter(obj["data"], trainable=obj["trainable"], name=obj["name"])
            return p
        if obj.get("__paddle_tpu_tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"], stop_gradient=obj["stop_gradient"])
            t.name = obj.get("name")
            return t
        return {k: _from_storable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_storable(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_to_storable(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs):
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    return _from_storable(obj, return_numpy=return_numpy)
