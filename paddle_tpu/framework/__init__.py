"""placeholder — populated in later milestones this round."""
