"""paddle_tpu.metric — training metrics.

Reference parity: ``paddle.metric`` (python/paddle/metric/metrics.py):
Metric base (compute/update/accumulate/reset/name), Accuracy, Precision,
Recall, Auc.  Computation happens on host numpy — metrics are control-plane,
not device-plane (keeping them out of the jitted step avoids recompiles).
"""

from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _to_np(x):
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        """Optional device-side preprocessing; default passthrough."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        super().__init__(name)
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred_np = _to_np(pred)
        label_np = _to_np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        num = int(np.prod(correct.shape[:-1]))
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].any(-1).sum()
            self.count[i] += num
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_to_np(preds) > 0.5).astype(int).ravel()
        labels = _to_np(labels).astype(int).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_to_np(preds) > 0.5).astype(int).ravel()
        labels = _to_np(labels).astype(int).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """ROC-AUC via thresholded confusion bins (reference metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _to_np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.ravel()
        labels = _to_np(labels).astype(int).ravel()
        bins = np.round(preds * self.num_thresholds).astype(int)
        bins = np.clip(bins, 0, self.num_thresholds)
        np.add.at(self._stat_pos, bins[labels == 1], 1)
        np.add.at(self._stat_neg, bins[labels == 0], 1)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0
