"""Persistent AOT-executable cache + model-artifact bundles (ROADMAP 5).

Production fleets restart constantly — elastic drills it, serving
replicas scale up under load — and every restart used to re-trace and
re-compile every executable: TrainStep, decode, every prefill bucket /
chunk, the spec-verify forward.  This module makes compiled XLA
executables a *shippable artifact*: ``jax.experimental.
serialize_executable`` bytes in a content-addressed on-disk cache, so a
fresh process deserialize-and-loads in milliseconds instead of paying
trace + XLA compile.

Cache discipline (the autotune-cache v2 rules, applied to binaries):

* **Content-addressed keys** — sha256 over (target, argument signature
  from :func:`~paddle_tpu.observability.device_profiler.signature_of`
  — the same pytree-structure + leaf-aval string ``jax.jit`` keys its
  executable cache on, i.e. the ``compile_records`` key — mesh shape +
  axis names, per-param shardings, jax version, backend/platform
  fingerprint, and an ``extra`` discriminator for config the caller
  closed over).  One entry file per key; no shared index to corrupt.
* **Versioned schema** — every entry embeds ``schema``; an old-schema,
  corrupt, or truncated entry is silently invalidated (treated as a
  miss, unlinked best-effort), never raised.
* **Atomic writes** — entries land via tmp-file + ``os.replace`` so a
  concurrent reader can never observe a half-written executable.
* **Backend fencing** — the backend fingerprint (platform, device kind,
  device count) is in the key AND re-verified at load, so a CPU entry
  can never be served to a TPU process (or vice versa), and a
  wrong-jax-version entry falls through to live compilation.
* **Counters** — ``paddle_tpu_compile_cache_total{target,result}``
  (hit / miss / store / deserialize_error) in the default metrics
  registry; a hit runs under a ``compile.cache_hit`` tracer span.
* **Graceful fall-through** — every cache code path is wrapped: any
  lookup or deserialization failure degrades to live compilation.  A
  stale cache must never be able to break a boot.

On top, :func:`bundle` / :func:`load_bundle` package a *model artifact*:
checkpoint weights (the digested index from ``distributed.checkpoint``)
+ serialized executables + tuned block sizes from the autotune cache —
everything a drained elastic worker or a brand-new serving replica
needs to go from empty disk to first token without a single XLA
compile.

Env knobs:
  PADDLE_TPU_COMPILE_CACHE=1        enable (default off — opt-in, like
                                    PADDLE_TPU_PAGED_KV)
  PADDLE_TPU_COMPILE_CACHE_DIR=path cache directory (default
                                    ~/.cache/paddle_tpu/executables)

CLI::

    python -m paddle_tpu.compile_cache stats
    python -m paddle_tpu.compile_cache bundle OUT --checkpoint CKPT
    python -m paddle_tpu.compile_cache load-bundle PATH
    python -m paddle_tpu.compile_cache clear
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import pickle
import shutil
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

__all__ = ["SCHEMA_VERSION", "enabled", "cache_dir", "backend_fingerprint",
           "cache_key", "lookup", "store", "aot_compile_cached",
           "model_config_tag", "cached_entries", "clear_cache",
           "cache_stats", "bundle", "load_bundle", "main"]

SCHEMA_VERSION = 1

# in-memory layer: a process that stored an entry (or already loaded it)
# never re-reads / re-deserializes the file
_mem: Dict[str, Any] = {}


# -- knobs + keys ------------------------------------------------------------

def enabled() -> bool:
    """Opt-in: ``PADDLE_TPU_COMPILE_CACHE=1``.  Default off — loading a
    serialized binary is semantically identical to recompiling, but the
    knob keeps cold-start behaviour explicit, like PADDLE_TPU_PAGED_KV."""
    return os.environ.get("PADDLE_TPU_COMPILE_CACHE", "0") == "1"


def cache_dir() -> str:
    return os.environ.get(
        "PADDLE_TPU_COMPILE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "executables"))


def backend_fingerprint() -> str:
    """Platform + device kind + device count — the hardware assembly an
    executable was compiled for.  In the key AND re-checked at load:
    disjoint namespaces, so a CPU test run can never poison (or serve)
    a TPU boot."""
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "?").replace(" ", "_")
        return f"{dev.platform}:{kind}:n{jax.device_count()}"
    except Exception:
        return "unknown:?:n0"


def _mesh_tag(mesh) -> str:
    if mesh is None:
        return "nomesh"
    try:
        return ",".join(f"{a}={s}" for a, s in mesh.shape.items())
    except Exception:
        return repr(mesh)


def _shardings_tag(shardings) -> str:
    if not shardings:
        return "nosharding"
    try:
        items = sorted(shardings.items())
        return ";".join(
            f"{n}:{getattr(sh, 'spec', sh)}" for n, sh in items)
    except Exception:
        return repr(shardings)


def cache_key(target: str, signature: str, mesh=None, shardings=None,
              extra: str = "") -> str:
    """Content address of one executable.  ``signature`` is
    ``signature_of((args, kwargs))`` — the jaxpr-level call signature;
    ``extra`` carries closed-over config the avals can't see (sampling
    params, accumulation steps, optimizer hyperparameters, …)."""
    material = "\x1f".join([
        f"schema{SCHEMA_VERSION}", target, signature,
        _mesh_tag(mesh), _shardings_tag(shardings),
        f"jax{jax.__version__}", backend_fingerprint(), extra])
    return hashlib.sha256(material.encode()).hexdigest()


def _entry_path(key: str, root: Optional[str] = None) -> str:
    return os.path.join(root or cache_dir(), f"{key}.exe")


def model_config_tag(model) -> str:
    """Key discriminator for config a model BAKES into its trace as
    constants (rope tables, norm epsilons, …): the avals of the call
    arguments can't see those, so two models with identical parameter
    shapes but different config must not share an executable."""
    cfg = getattr(model, "config", None)
    if cfg is None:
        return type(model).__name__
    try:
        d = sorted((k, repr(v)) for k, v in vars(cfg).items()
                   if not k.startswith("_"))
        digest = hashlib.sha256(repr(d).encode()).hexdigest()[:16]
    except TypeError:
        digest = hashlib.sha256(repr(cfg).encode()).hexdigest()[:16]
    return f"{type(model).__name__}:{digest}"


# -- telemetry ---------------------------------------------------------------

def _counter():
    from paddle_tpu.observability import default_registry
    return default_registry().counter(
        "paddle_tpu_compile_cache_total",
        "persistent executable-cache lookups/stores by outcome",
        labelnames=("target", "result"))


def _count(target: str, result: str):
    try:
        _counter().labels(target=target, result=result).inc()
    except Exception:
        pass


# -- entry io ----------------------------------------------------------------

def _read_entry(path: str) -> Optional[dict]:
    """Parse + validate one entry file.  None on missing / truncated /
    corrupt / old-schema / wrong-jax-version / wrong-backend — silent
    invalidation (stale files are unlinked best-effort), never raises."""
    try:
        with open(path, "rb") as f:
            entry = pickle.load(f)
    except FileNotFoundError:
        return None
    except Exception:
        _unlink_quiet(path)
        return None
    if not isinstance(entry, dict) \
            or entry.get("schema") != SCHEMA_VERSION \
            or entry.get("jax_version") != jax.__version__ \
            or entry.get("backend") != backend_fingerprint():
        _unlink_quiet(path)
        return None
    if not isinstance(entry.get("payload"), bytes):
        _unlink_quiet(path)
        return None
    return entry


def _unlink_quiet(path: str):
    try:
        os.remove(path)
    except OSError:
        pass


def _write_entry(path: str, entry: dict) -> bool:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(entry, f)
        os.replace(tmp, path)
        return True
    except Exception:
        return False   # read-only fs: the in-memory layer still works


def lookup(key: str, target: str = "fn", root: Optional[str] = None):
    """Deserialize-and-load the cached executable for ``key``, or None.
    The load runs under a ``compile.cache_hit`` span; a payload that no
    longer deserializes counts ``deserialize_error`` and falls through
    (the stale entry is removed so the next boot doesn't retry it)."""
    if key in _mem:
        _count(target, "hit")
        return _mem[key]
    path = _entry_path(key, root)
    entry = _read_entry(path)
    if entry is None:
        _count(target, "miss")
        return None
    try:
        from jax.experimental import serialize_executable as se

        from paddle_tpu.observability.tracing import tracer
        with tracer().span("compile.cache_hit", target=target,
                           key=key[:12]):
            t0 = time.perf_counter()
            compiled = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
            load_s = time.perf_counter() - t0
    except Exception:
        _count(target, "deserialize_error")
        _unlink_quiet(path)
        return None
    _mem[key] = compiled
    _count(target, "hit")
    _record_hit(target, entry, load_s)
    return compiled


def _record_hit(target: str, entry: dict, load_s: float):
    """A cache hit joins the compile log (so ``compile_records`` shows
    the boot's executables) WITHOUT touching paddle_tpu_compile_total —
    that counter means 'explicit XLA compiles', and the whole point of
    a hit is that none happened."""
    try:
        from paddle_tpu.observability.device_profiler import (
            CompileInfo, ExecutableStats, record_compile_info)
        st = ExecutableStats(**(entry.get("stats") or {}))
        record_compile_info(CompileInfo(
            target=target, signature=entry.get("signature", ""),
            lower_s=0.0, compile_s=load_s, stats=st, cached=True))
    except Exception:
        pass
    try:
        from paddle_tpu.observability.recorder import flight_recorder
        flight_recorder().record("compile.cache_hit", target=target,
                                 load_s=round(load_s, 4))
    except Exception:
        pass


def store(key: str, compiled, target: str = "fn", signature: str = "",
          stats: Optional[dict] = None, root: Optional[str] = None) -> bool:
    """Serialize ``compiled`` into the cache.  Unserializable
    executables (backends without PjRt executable serialization) and io
    failures degrade to False — the live executable keeps working."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
    except Exception:
        return False
    entry = {
        "schema": SCHEMA_VERSION,
        "jax_version": jax.__version__,
        "backend": backend_fingerprint(),
        "target": target,
        "signature": signature,
        "stats": stats or {},
        "payload": payload,
        "in_tree": in_tree,
        "out_tree": out_tree,
        "created": time.time(),
    }
    ok = _write_entry(_entry_path(key, root), entry)
    if ok:
        _mem[key] = compiled
        _count(target, "store")
    return ok


def aot_compile_cached(fn: Callable, *args, target: str = "fn",
                       mesh=None, shardings=None, extra: str = "",
                       registry=None, cache_only: bool = False,
                       **kwargs):
    """:func:`~paddle_tpu.observability.device_profiler.aot_compile`
    with the persistent cache in front.

    Hit → deserialize-and-load (no trace, no XLA compile, no
    ``paddle_tpu_compile_total`` bump) under a ``compile.cache_hit``
    span.  Miss → live ``lower().compile()`` with full compile
    observability, then stored.  Returns ``(compiled, CompileInfo,
    hit)``; with ``cache_only=True`` a miss returns ``(None, None,
    False)`` instead of compiling (the _recover re-warm path: consult
    the cache, never pay a compile inside fault recovery)."""
    from paddle_tpu.observability.device_profiler import (
        CompileInfo, ExecutableStats, aot_compile, compiled_stats,
        signature_of)

    if not enabled():
        if cache_only:
            return None, None, False
        compiled, info = aot_compile(fn, *args, target=target,
                                     registry=registry, **kwargs)
        return compiled, info, False

    signature = signature_of((args, kwargs))
    key = cache_key(target, signature, mesh=mesh, shardings=shardings,
                    extra=extra)
    t0 = time.perf_counter()
    compiled = lookup(key, target=target)
    if compiled is not None:
        st = compiled_stats(compiled)
        # compile_s carries the deserialize-and-load wall time: the
        # cold-start ledger's 'compile_or_load' column on the hit path
        info = CompileInfo(target=target, signature=signature,
                           lower_s=0.0,
                           compile_s=time.perf_counter() - t0,
                           stats=st, cached=True)
        return compiled, info, True
    if cache_only:
        return None, None, False
    compiled, info = aot_compile(fn, *args, target=target,
                                 registry=registry, **kwargs)
    store(key, compiled, target=target, signature=signature,
          stats=_stats_dict(info.stats))
    return compiled, info, False


def _stats_dict(stats) -> dict:
    import dataclasses
    try:
        return dataclasses.asdict(stats)
    except Exception:
        return {}


# -- inventory ---------------------------------------------------------------

def cached_entries(root: Optional[str] = None) -> List[dict]:
    """Metadata rows (no payload) of every VALID entry in the cache —
    invalid files are skipped (and invalidated) exactly as a lookup
    would."""
    root = root or cache_dir()
    rows = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return rows
    for name in names:
        if not name.endswith(".exe"):
            continue
        entry = _read_entry(os.path.join(root, name))
        if entry is None:
            continue
        rows.append({"key": name[:-4], "target": entry["target"],
                     "signature": entry.get("signature", "")[:80],
                     "bytes": len(entry["payload"]),
                     "created": entry.get("created", 0.0)})
    return rows


def clear_cache(root: Optional[str] = None):
    root = root or cache_dir()
    _mem.clear()
    try:
        for name in os.listdir(root):
            if name.endswith(".exe") or ".exe.tmp." in name:
                _unlink_quiet(os.path.join(root, name))
    except OSError:
        pass


def reset_memory():
    """Forget in-process loaded executables (tests that swap
    PADDLE_TPU_COMPILE_CACHE_DIR)."""
    _mem.clear()


def cache_stats(root: Optional[str] = None) -> dict:
    rows = cached_entries(root)
    return {"entries": len(rows),
            "bytes": sum(r["bytes"] for r in rows),
            "targets": sorted({r["target"] for r in rows})}


# -- model-artifact bundle ---------------------------------------------------

BUNDLE_SCHEMA = 1


def bundle(out_dir: str, *, state_dict: Optional[Dict[str, Any]] = None,
           checkpoint_dir: Optional[str] = None,
           targets: Optional[List[str]] = None,
           cache_root: Optional[str] = None,
           note: str = "") -> dict:
    """Package a versioned model artifact: weights + executables +
    tuned block sizes, so a new replica boots from empty disk to first
    token with zero XLA compiles.

    * weights: either ``state_dict`` (saved here via the checksummed
      ``distributed.checkpoint`` writer) or an existing
      ``checkpoint_dir`` (copied, digests and all);
    * executables: every valid compile-cache entry (optionally filtered
      to ``targets``);
    * autotune: the merged block-size entries visible to this process
      (seed layer + user cache), written in the v2 schema.

    Returns the manifest dict (also written as ``MANIFEST.json``)."""
    os.makedirs(out_dir, exist_ok=True)
    manifest: dict = {"schema": BUNDLE_SCHEMA,
                      "jax_version": jax.__version__,
                      "backend": backend_fingerprint(),
                      "created": time.time(), "note": note}

    # weights --------------------------------------------------------------
    ckpt_out = os.path.join(out_dir, "checkpoint")
    if state_dict is not None:
        from paddle_tpu.distributed.checkpoint import save_state_dict
        save_state_dict(state_dict, ckpt_out)
        manifest["checkpoint"] = "checkpoint"
    elif checkpoint_dir is not None:
        if os.path.abspath(checkpoint_dir) != os.path.abspath(ckpt_out):
            if os.path.isdir(ckpt_out):
                shutil.rmtree(ckpt_out)
            shutil.copytree(checkpoint_dir, ckpt_out)
        manifest["checkpoint"] = "checkpoint"
    else:
        manifest["checkpoint"] = None

    # executables ----------------------------------------------------------
    exe_dir = os.path.join(out_dir, "executables")
    os.makedirs(exe_dir, exist_ok=True)
    copied = []
    root = cache_root or cache_dir()
    for row in cached_entries(root):
        if targets is not None and row["target"] not in targets:
            continue
        src = _entry_path(row["key"], root)
        try:
            shutil.copy2(src, os.path.join(exe_dir, f"{row['key']}.exe"))
            copied.append({"key": row["key"], "target": row["target"],
                           "bytes": row["bytes"]})
        except OSError:
            continue
    manifest["executables"] = copied

    # tuned block sizes ----------------------------------------------------
    try:
        from paddle_tpu.ops.pallas import autotune as at
        entries = at.cached_entries()
        with open(os.path.join(out_dir, "autotune.json"), "w") as f:
            json.dump({"version": at.CACHE_VERSION, "entries": entries},
                      f, indent=0, sort_keys=True)
        manifest["autotune_entries"] = len(entries)
    except Exception:
        manifest["autotune_entries"] = 0

    with open(os.path.join(out_dir, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    return manifest


def load_bundle(path: str, *, cache_root: Optional[str] = None,
                install_autotune: bool = True,
                restore_weights: bool = True) -> dict:
    """Unpack a model artifact onto this machine:

    * executables are installed into the active compile cache (invalid
      / wrong-backend entries are skipped silently — a bundle built on
      another fleet must not poison this one);
    * autotune entries merge into the persistent block-size cache;
    * weights are restored (``{name: np.ndarray}``) from the bundled
      checkpoint when present.

    Returns ``{"manifest", "installed", "skipped", "autotune_entries",
    "state_dict"}``.  Raises ValueError on a missing/old-schema
    manifest — loading a bundle is an explicit operation, unlike the
    silent per-entry invalidation."""
    man_path = os.path.join(path, "MANIFEST.json")
    try:
        with open(man_path) as f:
            manifest = json.load(f)
    except Exception as e:
        raise ValueError(f"not a model bundle (no readable MANIFEST.json "
                         f"at {path}): {e}")
    if manifest.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(f"bundle schema {manifest.get('schema')!r} != "
                         f"supported {BUNDLE_SCHEMA}")

    root = cache_root or cache_dir()
    installed, skipped = [], 0
    exe_dir = os.path.join(path, "executables")
    if os.path.isdir(exe_dir):
        for name in sorted(os.listdir(exe_dir)):
            if not name.endswith(".exe"):
                continue
            entry = _read_entry(os.path.join(exe_dir, name))
            if entry is None:         # wrong backend/jax/schema: skip
                skipped += 1
                continue
            if _write_entry(_entry_path(name[:-4], root), entry):
                installed.append(entry["target"])
            else:
                skipped += 1

    n_autotune = 0
    if install_autotune:
        try:
            from paddle_tpu.ops.pallas import autotune as at
            loaded = at._parse(os.path.join(path, "autotune.json"))
            if loaded:
                at._load()
                at._mem_cache.update(loaded)
                at._save()
                n_autotune = len(loaded)
        except Exception:
            n_autotune = 0

    state = None
    if restore_weights and manifest.get("checkpoint"):
        try:
            from paddle_tpu.distributed.checkpoint import load_state_dict
            state = load_state_dict(
                os.path.join(path, manifest["checkpoint"]))
        except Exception:
            state = None

    try:
        from paddle_tpu.observability.recorder import flight_recorder
        flight_recorder().record("compile_cache.load_bundle", path=path,
                                 installed=len(installed),
                                 skipped=skipped,
                                 autotune=n_autotune)
    except Exception:
        pass
    return {"manifest": manifest, "installed": installed,
            "skipped": skipped, "autotune_entries": n_autotune,
            "state_dict": state}


# -- CLI ---------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.compile_cache",
        description="Persistent AOT executable cache + model-artifact "
                    "bundles (second-scale cold start).")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("stats", help="list valid cache entries")
    sub.add_parser("clear", help="remove every cache entry")
    b = sub.add_parser("bundle", help="package weights + executables + "
                                      "tuned block sizes")
    b.add_argument("out", help="bundle directory to write")
    b.add_argument("--checkpoint", default=None,
                   help="existing distributed.checkpoint dir to include")
    b.add_argument("--targets", default=None,
                   help="comma-separated executable targets to include "
                        "(default: all)")
    b.add_argument("--note", default="", help="free-form manifest note")
    lb = sub.add_parser("load-bundle", help="install a bundle onto this "
                                            "machine")
    lb.add_argument("path")
    lb.add_argument("--no-autotune", action="store_true")
    args = ap.parse_args(argv)

    if args.cmd == "stats":
        st = cache_stats()
        print(json.dumps({"dir": cache_dir(), **st,
                          "enabled": enabled()}, indent=1))
        for row in cached_entries():
            print(f"  {row['key'][:12]}  {row['bytes']:>10d}B  "
                  f"{row['target']}")
        return 0
    if args.cmd == "clear":
        n = len(cached_entries())
        clear_cache()
        print(f"cleared {n} entries from {cache_dir()}")
        return 0
    if args.cmd == "bundle":
        targets = [t.strip() for t in args.targets.split(",")] \
            if args.targets else None
        man = bundle(args.out, checkpoint_dir=args.checkpoint,
                     targets=targets, note=args.note)
        print(f"bundle {args.out}: {len(man['executables'])} "
              f"executables, {man['autotune_entries']} autotune "
              f"entries, checkpoint={man['checkpoint']}")
        return 0
    if args.cmd == "load-bundle":
        out = load_bundle(args.path,
                          install_autotune=not args.no_autotune)
        print(f"installed {len(out['installed'])} executables "
              f"({out['skipped']} skipped), {out['autotune_entries']} "
              f"autotune entries, weights="
              f"{'yes' if out['state_dict'] is not None else 'no'}")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
