"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built ground-up on JAX/XLA/Pallas.

Top-level namespace mirrors `paddle`: tensor ops live here, `nn`, `optimizer`,
`amp`, `io`, `jit`, `static`, `distributed`, `incubate`, `vision` are
submodules.  See SURVEY.md for the reference layer map this design answers.
"""

from __future__ import annotations

# dtypes / state first (no deps)
from paddle_tpu.core.dtypes import (  # noqa: F401
    bfloat16, bool_, complex64, complex128, float16, float32, float64,
    int8, int16, int32, int64, uint8,
)
from paddle_tpu.core.state import (  # noqa: F401
    get_default_dtype, seed, set_default_dtype,
)
from paddle_tpu.core.tensor import (  # noqa: F401
    Parameter, Tensor, enable_grad, is_grad_enabled, no_grad, set_grad_enabled,
)

# op surface → top level (paddle parity)
from paddle_tpu.ops.creation import *  # noqa: F401,F403
from paddle_tpu.ops.creation import to_tensor  # noqa: F401
from paddle_tpu.ops import linalg  # noqa: F401  (paddle.linalg namespace)
from paddle_tpu.ops.math import *  # noqa: F401,F403
from paddle_tpu.ops.linalg import *  # noqa: F401,F403
from paddle_tpu.ops.manipulation import *  # noqa: F401,F403
from paddle_tpu.ops.array_ops import (  # noqa: F401
    array_length, array_read, array_write, create_array,
)
from paddle_tpu.ops.logic import *  # noqa: F401,F403
from paddle_tpu.ops.search import *  # noqa: F401,F403
from paddle_tpu.ops.stat import *  # noqa: F401,F403
from paddle_tpu.ops.random import (  # noqa: F401
    bernoulli, multinomial, normal, poisson, rand, rand_like, randint,
    randint_like, randn, randn_like, randperm, standard_normal, uniform,
)

# method/dunder installation (must come after ops import)
import paddle_tpu.core.tensor_methods  # noqa: F401,E402

# submodules
from paddle_tpu import amp  # noqa: F401,E402
from paddle_tpu import audio  # noqa: F401,E402
from paddle_tpu import autograd  # noqa: F401,E402
from paddle_tpu import device  # noqa: F401,E402
from paddle_tpu import distributed  # noqa: F401,E402
from paddle_tpu import distribution  # noqa: F401,E402
from paddle_tpu import framework  # noqa: F401,E402
# `import` (not `from ... import`): the generated top-level `fft` OP is
# already bound on the package, and `from paddle_tpu import fft` would
# return that function; importing the submodule rebinds the attr to the
# module — paddle parity (paddle.fft is the namespace, paddle.fft.fft
# the transform)
import paddle_tpu.fft  # noqa: F401,E402
from paddle_tpu import geometric  # noqa: F401,E402
from paddle_tpu import hapi  # noqa: F401,E402
from paddle_tpu import analysis  # noqa: F401,E402
from paddle_tpu import incubate  # noqa: F401,E402
from paddle_tpu.hapi import Model  # noqa: F401,E402
from paddle_tpu.hapi.summary import flops, summary  # noqa: F401,E402
from paddle_tpu import io  # noqa: F401,E402
from paddle_tpu import jit  # noqa: F401,E402
from paddle_tpu import metric  # noqa: F401,E402
from paddle_tpu import nn  # noqa: F401,E402
from paddle_tpu import optimizer  # noqa: F401,E402
from paddle_tpu import observability  # noqa: F401,E402
from paddle_tpu import profiler  # noqa: F401,E402
from paddle_tpu import robustness  # noqa: F401,E402
from paddle_tpu import sparse  # noqa: F401,E402
from paddle_tpu import text  # noqa: F401,E402
from paddle_tpu import hub  # noqa: F401,E402
from paddle_tpu import onnx  # noqa: F401,E402
from paddle_tpu import static  # noqa: F401,E402
from paddle_tpu import utils  # noqa: F401,E402
from paddle_tpu import vision  # noqa: F401,E402
from paddle_tpu.device import get_device, set_device  # noqa: F401,E402
from paddle_tpu.framework.io_ import load, save  # noqa: F401,E402
from paddle_tpu.autograd import grad  # noqa: F401,E402
from paddle_tpu.flags import get_flags, set_flags  # noqa: F401,E402

from paddle_tpu.version import __version__  # noqa: F401,E402

# paddle-parity helpers
def in_dynamic_mode():
    import jax.core
    return True


CPUPlace = str
