"""paddle.geometric parity: graph segment math + message passing.

Reference: python/paddle/geometric/ (math.py segment_* over phi
segment_pool kernels; message_passing/send_recv.py send_u_recv /
send_ue_recv / send_uv over graph_send_recv kernels).  TPU-native: all
of these are jax segment reductions / gathers — XLA lowers them to
sorted-scatter, fully differentiable and fusible, no custom kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _num_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    if isinstance(segment_ids, jax.core.Tracer):
        raise ValueError(
            "segment count is data-dependent; pass num_segments=/out_size= "
            "when calling geometric ops inside jit (the reference's static "
            "mode requires the same)")
    # eager path: ids are concrete, match the reference (max id + 1)
    return int(jnp.max(segment_ids)) + 1 if segment_ids.size else 0


@eager_op
def segment_sum(data, segment_ids, name=None, num_segments=None):
    """Sum rows of `data` sharing a segment id (reference math.py:23);
    result has max(id)+1 rows (pass num_segments= inside jit)."""
    return jax.ops.segment_sum(
        data, segment_ids,
        num_segments=_num_segments(segment_ids, num_segments))


@eager_op
def segment_mean(data, segment_ids, name=None, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    total = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    count = jax.ops.segment_sum(jnp.ones_like(segment_ids,
                                              dtype=data.dtype),
                                segment_ids, num_segments=n)
    shape = (n,) + (1,) * (data.ndim - 1)
    return total / jnp.maximum(count.reshape(shape), 1)


@eager_op
def segment_min(data, segment_ids, name=None, num_segments=None):
    return jax.ops.segment_min(
        data, segment_ids,
        num_segments=_num_segments(segment_ids, num_segments))


@eager_op
def segment_max(data, segment_ids, name=None, num_segments=None):
    return jax.ops.segment_max(
        data, segment_ids,
        num_segments=_num_segments(segment_ids, num_segments))


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled explicitly
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _reduce(messages, dst_index, reduce_op, n):
    if reduce_op == "mean":
        total = jax.ops.segment_sum(messages, dst_index, num_segments=n)
        count = jax.ops.segment_sum(
            jnp.ones_like(dst_index, dtype=messages.dtype), dst_index,
            num_segments=n)
        shape = (n,) + (1,) * (messages.ndim - 1)
        return total / jnp.maximum(count.reshape(shape), 1)
    if reduce_op not in _REDUCERS or _REDUCERS[reduce_op] is None:
        raise ValueError(f"unknown reduce_op {reduce_op}")
    out = _REDUCERS[reduce_op](messages, dst_index, num_segments=n)
    if reduce_op in ("min", "max"):
        # untouched rows come back +-inf from jax; the reference zeros them
        touched = jax.ops.segment_sum(jnp.ones_like(dst_index), dst_index,
                                      num_segments=n) > 0
        shape = (n,) + (1,) * (messages.ndim - 1)
        out = jnp.where(touched.reshape(shape), out, 0)
    return out


@eager_op
def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges, reduce onto dst
    (reference send_recv.py:36)."""
    n = int(out_size) if out_size is not None else x.shape[0]
    return _reduce(x[src_index], dst_index, reduce_op, n)


def _message(xe, ye, message_op):
    if message_op in ("add",):
        return xe + ye
    if message_op == "sub":
        return xe - ye
    if message_op == "mul":
        return xe * ye
    if message_op == "div":
        return xe / ye
    raise ValueError(f"unknown message_op {message_op}")


@eager_op
def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, reduce onto dst
    (reference send_recv.py:179)."""
    n = int(out_size) if out_size is not None else x.shape[0]
    return _reduce(_message(x[src_index], y, message_op), dst_index,
                   reduce_op, n)


@eager_op
def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints: combine x[src] with y[dst]
    (reference message_passing/send_recv.py send_uv)."""
    return _message(x[src_index], y[dst_index], message_op)
