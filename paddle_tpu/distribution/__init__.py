"""paddle.distribution parity surface.

Reference: python/paddle/distribution/__init__.py — Bernoulli, Beta,
Categorical, Cauchy, Dirichlet, Distribution, ExponentialFamily, Geometric,
Gumbel, Independent, Laplace, LogNormal, Multinomial, Normal, Uniform,
TransformedDistribution, kl_divergence/register_kl, and the transform zoo.
"""

from paddle_tpu.distribution.distribution import Distribution  # noqa: F401
from paddle_tpu.distribution.exponential_family import (  # noqa: F401
    ExponentialFamily)
from paddle_tpu.distribution.normal import LogNormal, Normal  # noqa: F401
from paddle_tpu.distribution.discrete import (  # noqa: F401
    Bernoulli, Categorical, Geometric, Multinomial)
from paddle_tpu.distribution.simplex import Beta, Dirichlet  # noqa: F401
from paddle_tpu.distribution.location_scale import (  # noqa: F401
    Cauchy, Gumbel, Laplace, Uniform)
from paddle_tpu.distribution.independent import Independent  # noqa: F401
from paddle_tpu.distribution.transform import *  # noqa: F401,F403
from paddle_tpu.distribution.transform import __all__ as _transform_all
from paddle_tpu.distribution.transformed_distribution import (  # noqa: F401
    TransformedDistribution)
from paddle_tpu.distribution.kl import (  # noqa: F401
    kl_divergence, register_kl)

__all__ = [
    "Bernoulli", "Beta", "Categorical", "Cauchy", "Dirichlet", "Distribution",
    "ExponentialFamily", "Geometric", "Gumbel", "Independent", "Laplace",
    "LogNormal", "Multinomial", "Normal", "TransformedDistribution",
    "Uniform", "kl_divergence", "register_kl",
] + list(_transform_all)
