"""Independent: reinterpret trailing batch dims as event dims.

Parity: reference python/paddle/distribution/independent.py.
"""

from __future__ import annotations

from paddle_tpu.distribution.distribution import Distribution

__all__ = ["Independent"]


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_ndims):
        if reinterpreted_batch_ndims > len(base.batch_shape):
            raise ValueError(
                "reinterpreted_batch_ndims exceeds base batch rank")
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        n = len(base.batch_shape) - self.reinterpreted_batch_ndims
        super().__init__(
            batch_shape=base.batch_shape[:n],
            event_shape=base.batch_shape[n:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_rightmost(self, x):
        if self.reinterpreted_batch_ndims == 0:
            return x
        axes = list(range(-self.reinterpreted_batch_ndims, 0))
        return x.sum(axis=axes)

    def log_prob(self, value):
        return self._sum_rightmost(self.base.log_prob(value))

    def entropy(self):
        return self._sum_rightmost(self.base.entropy())
