"""Beta and Dirichlet.

Parity: reference python/paddle/distribution/{beta,dirichlet}.py.
rsample uses jax.random.gamma/beta/dirichlet, which carry implicit
reparameterization gradients wrt the concentration parameters — routed
through the dispatcher so the draw is taped eagerly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import dispatch
from paddle_tpu.distribution.distribution import (Distribution, _as_tensor,
                                                  _broadcast_shape)

__all__ = ["Beta", "Dirichlet"]


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _as_tensor(alpha)
        self.beta = _as_tensor(beta)
        super().__init__(
            batch_shape=_broadcast_shape(self.alpha, self.beta))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def _log_beta_fn(self):
        return pp.lgamma(self.alpha) + pp.lgamma(self.beta) \
            - pp.lgamma(self.alpha + self.beta)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(tuple(shape))
        key = _state.next_key()

        def draw(a, b):
            ga = jax.random.gamma(key, jnp.broadcast_to(a, out_shape))
            gb = jax.random.gamma(jax.random.fold_in(key, 1),
                                  jnp.broadcast_to(b, out_shape))
            return ga / (ga + gb)

        return dispatch(draw, self.alpha, self.beta, op_name="beta_sample")

    def entropy(self):
        a, b = self.alpha, self.beta
        s = a + b
        return self._log_beta_fn() - (a - 1.0) * pp.digamma(a) \
            - (b - 1.0) * pp.digamma(b) + (s - 2.0) * pp.digamma(s)

    def log_prob(self, value):
        value = _as_tensor(value)
        return (self.alpha - 1.0) * pp.log(value) \
            + (self.beta - 1.0) * pp.log1p(-value) - self._log_beta_fn()


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _as_tensor(concentration)
        shape = tuple(self.concentration.shape)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(axis=-1,
                                                           keepdim=True)

    @property
    def variance(self):
        a0 = self.concentration.sum(axis=-1, keepdim=True)
        m = self.concentration / a0
        return m * (1.0 - m) / (a0 + 1.0)

    def rsample(self, shape=()):
        out_shape = self._extend_shape(tuple(shape))
        key = _state.next_key()

        def draw(conc):
            g = jax.random.gamma(key, jnp.broadcast_to(conc, out_shape))
            return g / g.sum(axis=-1, keepdims=True)

        return dispatch(draw, self.concentration, op_name="dirichlet_sample")

    def entropy(self):
        a = self.concentration
        a0 = a.sum(axis=-1)
        k = float(a.shape[-1])
        log_b = pp.lgamma(a).sum(axis=-1) - pp.lgamma(a0)
        return log_b + (a0 - k) * pp.digamma(a0) \
            - ((a - 1.0) * pp.digamma(a)).sum(axis=-1)

    def log_prob(self, value):
        value = _as_tensor(value)
        a = self.concentration
        a0 = a.sum(axis=-1)
        log_b = pp.lgamma(a).sum(axis=-1) - pp.lgamma(a0)
        return ((a - 1.0) * pp.log(value)).sum(axis=-1) - log_b
