"""KL divergence registry with (type(p), type(q)) multi-dispatch.

Parity: reference python/paddle/distribution/kl.py:37 (kl_divergence,
register_kl, MRO-based most-specific-match dispatch).
"""

from __future__ import annotations

import paddle_tpu as pp
from paddle_tpu.distribution.discrete import Bernoulli, Categorical, Geometric
from paddle_tpu.distribution.location_scale import Gumbel, Laplace, Uniform
from paddle_tpu.distribution.normal import LogNormal, Normal
from paddle_tpu.distribution.simplex import Beta, Dirichlet
from paddle_tpu.distribution.exponential_family import ExponentialFamily

__all__ = ["register_kl", "kl_divergence"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def decorator(f):
        _REGISTRY[(cls_p, cls_q)] = f
        return f
    return decorator


def _match_score(cls, reg_cls):
    try:
        return cls.__mro__.index(reg_cls)
    except ValueError:
        return None


def _dispatch(cls_p, cls_q):
    best, best_score = None, None
    for (rp, rq), fn in _REGISTRY.items():
        sp = _match_score(cls_p, rp)
        sq = _match_score(cls_q, rq)
        if sp is None or sq is None:
            continue
        score = (sp, sq)
        if best_score is None or score < best_score:
            best, best_score = fn, score
    return best


def kl_divergence(p, q):
    fn = _dispatch(type(p), type(q))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence not registered for ({type(p).__name__}, "
            f"{type(q).__name__}); use register_kl.")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1.0 - pp.log(var_ratio))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    eps = 1e-7
    a = pp.clip(p.probs, eps, 1 - eps)
    b = pp.clip(q.probs, eps, 1 - eps)
    return a * (pp.log(a) - pp.log(b)) + \
        (1.0 - a) * (pp.log1p(-a) - pp.log1p(-b))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    from paddle_tpu.nn.functional import log_softmax, softmax
    lp = log_softmax(p.logits, axis=-1)
    lq = log_softmax(q.logits, axis=-1)
    return (softmax(p.logits, axis=-1) * (lp - lq)).sum(axis=-1)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    # defined when support(p) ⊆ support(q)
    return pp.log((q.high - q.low) / (p.high - p.low))


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    r = p.scale / q.scale
    d = pp.abs(p.loc - q.loc) / q.scale
    return -pp.log(r) + r * pp.exp(-pp.abs(p.loc - q.loc) / p.scale) \
        + d - 1.0


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # KL = log(βq/βp) + γ(βp/βq - 1) + (μp-μq)/βq
    #      + exp((μq-μp)/βq)·Γ(1+βp/βq) - 1
    euler = 0.5772156649015329
    beta_r = p.scale / q.scale
    t = pp.exp((q.loc - p.loc) / q.scale) * pp.exp(pp.lgamma(1.0 + beta_r))
    return pp.log(q.scale) - pp.log(p.scale) + euler * (beta_r - 1.0) \
        + (p.loc - q.loc) / q.scale + t - 1.0


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    s_p = p.alpha + p.beta
    return (q._log_beta_fn() - p._log_beta_fn()
            + (p.alpha - q.alpha) * pp.digamma(p.alpha)
            + (p.beta - q.beta) * pp.digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta) * pp.digamma(s_p))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = a.sum(axis=-1)
    return (pp.lgamma(a0) - pp.lgamma(b.sum(axis=-1))
            - (pp.lgamma(a) - pp.lgamma(b)).sum(axis=-1)
            + ((a - b) * (pp.digamma(a)
                          - pp.unsqueeze(pp.digamma(a0), -1))).sum(axis=-1))


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    eps = 1e-7
    a = pp.clip(p.probs, eps, 1 - eps)
    b = pp.clip(q.probs, eps, 1 - eps)
    return (pp.log(a) - pp.log(b)) \
        + (1.0 / a - 1.0) * (pp.log1p(-a) - pp.log1p(-b))


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Bregman-divergence fallback for same-family pairs; requires matching
    natural parameterizations (reference kl.py _kl_expfamily_expfamily)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "generic exponential-family KL needs p and q of the same family")
    # KL(p||q) = A(η_q) - A(η_p) - <η_q - η_p, ∇A(η_p)>
    p_nat = [n.detach().clone() for n in p._natural_parameters]
    for e in p_nat:
        e.stop_gradient = False
    lp = p._log_normalizer(*p_nat)
    grads = pp.grad(lp.sum(), p_nat, allow_unused=True)
    kl = q._log_normalizer(*q._natural_parameters) - lp
    for pn, qn, g in zip(p_nat, q._natural_parameters, grads):
        if g is not None:
            kl = kl - (qn - pn.detach()) * g
    return kl
