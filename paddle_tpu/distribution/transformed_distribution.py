"""TransformedDistribution: push a base distribution through transforms.

Parity: reference python/paddle/distribution/transformed_distribution.py.
"""

from __future__ import annotations

from paddle_tpu.distribution.distribution import Distribution, _as_tensor
from paddle_tpu.distribution.transform import ChainTransform, Transform

__all__ = ["TransformedDistribution"]


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        for t in transforms:
            if not isinstance(t, Transform):
                raise TypeError(f"expected Transform, got {type(t)}")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        shape = chain.forward_shape(base.batch_shape + base.event_shape)
        # event rank can only grow through transforms; batch rank preserved
        nb = len(base.batch_shape)
        super().__init__(batch_shape=tuple(shape[:nb]),
                         event_shape=tuple(shape[nb:]))

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        value = _as_tensor(value)
        lp = 0.0
        y = value
        # event rank is tracked per stage: each transform maps a domain of
        # _domain_event_dim event dims onto _codomain_event_dim of them
        event_rank = len(self.event_shape)
        for t in reversed(self.transforms):
            if not t._is_injective:
                raise ValueError(
                    f"log_prob is undefined through non-injective transform "
                    f"{type(t).__name__}")
            x = t.inverse(y)
            ld = t.forward_log_det_jacobian(x)
            # sum the per-element log-det over event dims the transform does
            # not already reduce (torch/paddle rule: event_dim - codomain dim)
            extra = event_rank - t._codomain_event_dim
            if hasattr(ld, "shape") and extra > 0 and len(ld.shape) > 0:
                axes = list(range(-min(extra, len(ld.shape)), 0))
                ld = ld.sum(axis=axes)
            lp = lp - ld
            y = x
            event_rank = event_rank - t._codomain_event_dim \
                + t._domain_event_dim
        return lp + self.base.log_prob(y)
