"""Bijective transforms for TransformedDistribution.

Parity: reference python/paddle/distribution/transform.py:59 (Transform,
Abs/Affine/Chain/Exp/Independent/Power/Reshape/Sigmoid/Softmax/Stack/
StickBreaking/Tanh transforms).  The constraint/variable machinery is
replaced by the minimal injectivity flag the user API observes.
"""

from __future__ import annotations

import math
from functools import reduce

import numpy as np

import paddle_tpu as pp
from paddle_tpu.distribution.distribution import _as_tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Transform:
    _is_injective = True
    # event dims consumed by one application of the transform
    _domain_event_dim = 0
    _codomain_event_dim = 0

    def __call__(self, x):
        return self.forward(x)

    def forward(self, x):
        return self._forward(_as_tensor(x))

    def inverse(self, y):
        return self._inverse(_as_tensor(y))

    def forward_log_det_jacobian(self, x):
        x = _as_tensor(x)
        if hasattr(self, "_forward_log_det_jacobian"):
            return self._forward_log_det_jacobian(x)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return -self._inverse_log_det_jacobian(self._forward(x))
        raise NotImplementedError(
            f"{type(self).__name__} defines no log-det jacobian")

    def inverse_log_det_jacobian(self, y):
        y = _as_tensor(y)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return self._inverse_log_det_jacobian(y)
        # public fallback so composite transforms that only override the
        # public forward_log_det_jacobian (Chain/Independent/Stack) work
        return -self.forward_log_det_jacobian(self.inverse(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AbsTransform(Transform):
    """y = |x| — non-injective; inverse returns the positive branch."""
    _is_injective = False

    def _forward(self, x):
        return pp.abs(x)

    def _inverse(self, y):
        return y


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return pp.log(pp.abs(self.scale)) + x * 0.0


class ExpTransform(Transform):
    def _forward(self, x):
        return pp.exp(x)

    def _inverse(self, y):
        return pp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _as_tensor(power)

    def _forward(self, x):
        return pp.pow(x, self.power)

    def _inverse(self, y):
        return pp.pow(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return pp.log(pp.abs(self.power * pp.pow(x, self.power - 1.0)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return pp.nn.functional.sigmoid(x)

    def _inverse(self, y):
        return pp.log(y) - pp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        from paddle_tpu.nn.functional import softplus
        return -softplus(-x) - softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return pp.tanh(x)

    def _inverse(self, y):
        return 0.5 * (pp.log1p(y) - pp.log1p(-y))

    def _forward_log_det_jacobian(self, x):
        from paddle_tpu.nn.functional import softplus
        # log(1 - tanh(x)^2) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._is_injective = all(t._is_injective for t in self.transforms)

    def _forward(self, x):
        return reduce(lambda v, t: t.forward(v), self.transforms, x)

    def _inverse(self, y):
        return reduce(lambda v, t: t.inverse(v), reversed(self.transforms), y)

    def forward_log_det_jacobian(self, x):
        x = _as_tensor(x)
        total = None
        for t in self.transforms:
            term = t.forward_log_det_jacobian(x)
            total = term if total is None else total + term
            x = t.forward(x)
        return total

    def forward_shape(self, shape):
        return reduce(lambda s, t: t.forward_shape(s), self.transforms,
                      tuple(shape))

    def inverse_shape(self, shape):
        return reduce(lambda s, t: t.inverse_shape(s),
                      reversed(self.transforms), tuple(shape))


class IndependentTransform(Transform):
    """Sums the log-det over the trailing ``reinterpreted_batch_ndims``."""

    def __init__(self, base, reinterpreted_batch_ndims):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)
        self._is_injective = base._is_injective

    def _forward(self, x):
        return self.base.forward(x)

    def _inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ld = self.base.forward_log_det_jacobian(_as_tensor(x))
        axes = list(range(-self.reinterpreted_batch_ndims, 0))
        return ld.sum(axis=axes)

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_dim = len(self.in_event_shape)
        self._codomain_event_dim = len(self.out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(np.prod(self.out_event_shape)):
            raise ValueError("in_event_shape and out_event_shape must have "
                             "the same number of elements")

    def _forward(self, x):
        batch = list(x.shape[:len(x.shape) - len(self.in_event_shape)])
        return pp.reshape(x, batch + list(self.out_event_shape))

    def _inverse(self, y):
        batch = list(y.shape[:len(y.shape) - len(self.out_event_shape)])
        return pp.reshape(y, batch + list(self.in_event_shape))

    def _forward_log_det_jacobian(self, x):
        batch = list(x.shape[:len(x.shape) - len(self.in_event_shape)])
        return pp.zeros(batch or [1], dtype="float32")

    def forward_shape(self, shape):
        n = len(shape) - len(self.in_event_shape)
        return tuple(shape[:n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(shape) - len(self.out_event_shape)
        return tuple(shape[:n]) + self.in_event_shape


class SoftmaxTransform(Transform):
    """Normalizes exp(x) over the last axis; not bijective on R^n (the
    simplex loses one degree of freedom), so no log-det."""
    _is_injective = False
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        from paddle_tpu.nn.functional import softmax
        return softmax(x, axis=-1)

    def _inverse(self, y):
        return pp.log(y)


class StackTransform(Transform):
    """Applies a different transform to each slice along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._is_injective = all(t._is_injective for t in self.transforms)

    def _map(self, value, method):
        parts = pp.unbind(value, axis=self.axis)
        outs = [getattr(t, method)(v)
                for t, v in zip(self.transforms, parts)]
        return pp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map(x, "forward")

    def _inverse(self, y):
        return self._map(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._map(_as_tensor(x), "forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """R^(n) -> open simplex of dim n+1 via stick-breaking
    (reference transform.py StickBreakingTransform)."""
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        from paddle_tpu.nn.functional import sigmoid
        n = int(x.shape[-1])
        offset = pp.to_tensor(
            np.arange(n, 0, -1, dtype=np.float32))
        z = sigmoid(x - pp.log(offset))
        one = pp.ones(list(x.shape[:-1]) + [1], dtype="float32")
        zpad = pp.concat([1.0 - z, one], axis=-1)
        cum = pp.cumprod(zpad, dim=-1)
        cum_shifted = pp.concat([one, cum[..., :-1]], axis=-1)
        zfull = pp.concat([z, one], axis=-1)
        return zfull * cum_shifted

    def _inverse(self, y):
        n = int(y.shape[-1]) - 1
        cum = 1.0 - pp.cumsum(y, axis=-1)
        cum = cum[..., :-1]
        offset = pp.to_tensor(np.arange(n, 0, -1, dtype=np.float32))
        yk = y[..., :-1]
        z = yk / (yk + cum)
        return pp.log(z) - pp.log1p(-z) + pp.log(offset)

    def _forward_log_det_jacobian(self, x):
        from paddle_tpu.nn.functional import softplus
        n = int(x.shape[-1])
        offset = pp.to_tensor(np.arange(n, 0, -1, dtype=np.float32))
        xo = x - pp.log(offset)
        z = pp.nn.functional.sigmoid(xo)
        one = pp.ones(list(x.shape[:-1]) + [1], dtype="float32")
        rem = pp.cumprod(pp.concat([1.0 - z, one], axis=-1), dim=-1)
        rem_shifted = pp.concat([one, rem[..., :-1]], axis=-1)[..., :n]
        return (pp.log(rem_shifted) - softplus(-xo) - softplus(xo)).sum(axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
