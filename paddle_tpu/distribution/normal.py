"""Normal and LogNormal.

Parity: reference python/paddle/distribution/normal.py:89,
lognormal.py (LogNormal = exp-transformed Normal).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import wrap_like
from paddle_tpu.distribution.distribution import (Distribution, _as_tensor,
                                                  _broadcast_shape)

__all__ = ["Normal", "LogNormal"]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(batch_shape=_broadcast_shape(self.loc, self.scale))

    @property
    def mean(self):
        return pp.broadcast_to(self.loc, list(self.batch_shape)) \
            if self.batch_shape else self.loc

    @property
    def variance(self):
        v = self.scale * self.scale
        return pp.broadcast_to(v, list(self.batch_shape)) \
            if self.batch_shape else v

    def rsample(self, shape=()):
        out_shape = self._extend_shape(tuple(shape))
        eps = wrap_like(jax.random.normal(_state.next_key(), out_shape,
                                          jnp.float32))
        return self.loc + self.scale * eps

    def entropy(self):
        e = 0.5 + _HALF_LOG_2PI + pp.log(self.scale)
        return pp.broadcast_to(e, list(self.batch_shape)) \
            if self.batch_shape else e

    def log_prob(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return -0.5 * z * z - pp.log(self.scale) - _HALF_LOG_2PI

    def cdf(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / (self.scale * math.sqrt(2.0))
        return 0.5 * (1.0 + pp.erf(z))

    def icdf(self, value):
        value = _as_tensor(value)
        return self.loc + self.scale * math.sqrt(2.0) * pp.erfinv(
            2.0 * value - 1.0)


class LogNormal(Distribution):
    """exp(Normal(loc, scale)); direct closed forms instead of the
    reference's TransformedDistribution composition (lognormal.py)."""

    def __init__(self, loc, scale, name=None):
        self._base = Normal(loc, scale)
        self.loc = self._base.loc
        self.scale = self._base.scale
        super().__init__(batch_shape=self._base.batch_shape)

    @property
    def mean(self):
        return pp.exp(self.loc + 0.5 * self.scale * self.scale)

    @property
    def variance(self):
        s2 = self.scale * self.scale
        return pp.expm1(s2) * pp.exp(2.0 * self.loc + s2)

    def rsample(self, shape=()):
        return pp.exp(self._base.rsample(shape))

    def entropy(self):
        return self._base.entropy() + self._base.mean

    def log_prob(self, value):
        value = _as_tensor(value)
        logv = pp.log(value)
        return self._base.log_prob(logv) - logv
