"""ExponentialFamily: generic entropy via the log-normalizer gradient.

Parity: reference python/paddle/distribution/exponential_family.py —
entropy = -[sum_i eta_i * dA/deta_i - A(eta) + E[carrier measure]] computed
by differentiating the log normalizer; here that derivative comes from the
eager tape (grad on a taped A), exercising the same machinery as
paddle.grad(create_graph=...).
"""

from __future__ import annotations

import paddle_tpu as pp
from paddle_tpu.distribution.distribution import Distribution

__all__ = ["ExponentialFamily"]


class ExponentialFamily(Distribution):
    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        etas = [n.detach().clone() for n in self._natural_parameters]
        for e in etas:
            e.stop_gradient = False
        log_norm = self._log_normalizer(*etas)
        grads = pp.grad(log_norm.sum(), etas, create_graph=False,
                        allow_unused=True)
        result = -self._mean_carrier_measure + log_norm
        for eta, g in zip(etas, grads):
            if g is not None:
                result = result - eta.detach() * g
        return result
