"""Distribution base class.

Parity target: reference python/paddle/distribution/distribution.py:33
(Distribution: batch_shape/event_shape, sample/rsample, prob/log_prob,
entropy, kl_divergence).  TPU-native notes: all math routes through the
dispatcher ops so log_prob/entropy are tape-differentiable eagerly and
trace-transparent under jit; sampling draws from the process-global
splitting key (ops/random.py) so eager sampling is reproducible under
paddle.seed while rsample stays reparameterized (differentiable wrt the
distribution parameters).
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as pp
from paddle_tpu.core.tensor import Tensor

__all__ = ["Distribution"]


def _as_tensor(v, dtype="float32"):
    if isinstance(v, Tensor):
        return v
    arr = np.asarray(v)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if np.issubdtype(arr.dtype, np.integer):
        arr = arr.astype(np.float32)
    return pp.to_tensor(arr)


def _broadcast_shape(*tensors):
    shape = ()
    for t in tensors:
        shape = np.broadcast_shapes(shape, tuple(t.shape))
    return tuple(shape)


class Distribution:
    """Abstract base for probability distributions."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return pp.sqrt(self.variance)

    def sample(self, shape=()):
        """Non-differentiable draw of shape ``shape + batch + event``."""
        with pp.autograd.no_grad():
            out = self.rsample(shape)
        return out.detach() if hasattr(out, "detach") else out

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return pp.exp(self.log_prob(value))

    def probs(self, value):
        return self.prob(value)

    def kl_divergence(self, other):
        from paddle_tpu.distribution.kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")
