"""Discrete distributions: Bernoulli, Categorical, Multinomial, Geometric.

Parity: reference python/paddle/distribution/{bernoulli,categorical,
multinomial,geometric}.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import wrap_like
from paddle_tpu.distribution.distribution import (Distribution, _as_tensor,
                                                  _broadcast_shape)

__all__ = ["Bernoulli", "Categorical", "Multinomial", "Geometric"]

_EPS = 1e-7


def _clip_prob(p):
    return pp.clip(p, _EPS, 1.0 - _EPS)


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _as_tensor(probs)
        self.logits = pp.log(_clip_prob(self.probs)) - pp.log1p(
            -_clip_prob(self.probs))
        super().__init__(batch_shape=tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxed sample (reference bernoulli.py rsample)."""
        out_shape = self._extend_shape(tuple(shape))
        u = wrap_like(jax.random.uniform(_state.next_key(), out_shape,
                                         jnp.float32, minval=_EPS,
                                         maxval=1.0 - _EPS))
        logistic = pp.log(u) - pp.log1p(-u)
        return pp.nn.functional.sigmoid((self.logits + logistic) / temperature)

    def sample(self, shape=()):
        out_shape = self._extend_shape(tuple(shape))
        p = jnp.broadcast_to(self.probs._data, out_shape)
        return wrap_like(jax.random.bernoulli(_state.next_key(), p)
                         .astype(jnp.float32))

    def entropy(self):
        p = _clip_prob(self.probs)
        return -(p * pp.log(p) + (1.0 - p) * pp.log1p(-p))

    def log_prob(self, value):
        value = _as_tensor(value)
        p = _clip_prob(self.probs)
        return value * pp.log(p) + (1.0 - value) * pp.log1p(-p)

    def cdf(self, value):
        value = _as_tensor(value)
        zero = pp.zeros_like(value * self.probs)
        one = pp.ones_like(zero)
        mid = one - self.probs
        out = pp.where(value < 0.0, zero, pp.where(value < 1.0, mid, one))
        return out


class Categorical(Distribution):
    """Categorical over the last axis of ``logits``
    (reference categorical.py:87 — constructor takes logits)."""

    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)
        super().__init__(batch_shape=tuple(self.logits.shape[:-1]))
        self._n = int(self.logits.shape[-1])

    @property
    def probs_param(self):
        from paddle_tpu.nn.functional import softmax
        return softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        idx = jax.random.categorical(
            _state.next_key(), self.logits._data, axis=-1,
            shape=out_shape or None)
        return wrap_like(idx)  # int32 under x32, int64 when x64 enabled

    def entropy(self):
        from paddle_tpu.nn.functional import log_softmax, softmax
        logp = log_softmax(self.logits, axis=-1)
        p = softmax(self.logits, axis=-1)
        return -(p * logp).sum(axis=-1)

    def log_prob(self, value):
        from paddle_tpu.nn.functional import log_softmax
        logp = log_softmax(self.logits, axis=-1)
        idx = value if isinstance(value, pp.Tensor) else pp.to_tensor(value)
        idx_i = pp.cast(idx, "int32")
        onehot = pp.cast(
            wrap_like(jax.nn.one_hot(idx_i._data, self._n)), "float32")
        return (onehot * logp).sum(axis=-1)

    def probs(self, value):
        return pp.exp(self.log_prob(value))

    def kl_divergence(self, other):
        from paddle_tpu.distribution.kl import kl_divergence
        return kl_divergence(self, other)


class Multinomial(Distribution):
    """total_count trials over the category axis
    (reference multinomial.py:70)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs = _as_tensor(probs)
        p = self.probs
        self.probs = p / p.sum(axis=-1, keepdim=True)
        super().__init__(batch_shape=tuple(self.probs.shape[:-1]),
                         event_shape=(int(self.probs.shape[-1]),))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        logits = pp.log(_clip_prob(self.probs))._data
        n = self.total_count
        out_shape = tuple(shape) + self.batch_shape
        draws = jax.random.categorical(
            _state.next_key(), logits, axis=-1,
            shape=(n,) + out_shape if out_shape else (n,))
        k = int(self.probs.shape[-1])
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return wrap_like(counts.astype(jnp.float32))

    def entropy(self):
        """Exact: H = -lgamma(n+1) - n·Σ p_i log p_i + Σ_i E[lgamma(X_i+1)]
        with X_i ~ Binomial(n, p_i); the expectation is an explicit O(n)
        sum over the binomial pmf (no closed form exists)."""
        import numpy as np
        n = self.total_count
        p = _clip_prob(self.probs)
        k = pp.to_tensor(np.arange(n + 1, dtype=np.float32))
        lg_k1 = pp.lgamma(k + 1.0)
        # binomial log-pmf over a trailing k axis: (..., K, n+1)
        pk = pp.unsqueeze(p, -1)
        log_pmf = (pp.lgamma(pp.full_like(pk, float(n + 1)))
                   - lg_k1 - pp.lgamma(float(n) - k + 1.0)
                   + k * pp.log(pk) + (float(n) - k) * pp.log1p(-pk))
        e_lgamma = (pp.exp(log_pmf) * lg_k1).sum(axis=-1)
        import math
        return (-math.lgamma(n + 1)
                - float(n) * (p * pp.log(p)).sum(axis=-1)
                + e_lgamma.sum(axis=-1))

    def log_prob(self, value):
        value = _as_tensor(value)
        p = _clip_prob(self.probs)
        coeff = pp.lgamma(value.sum(axis=-1) + 1.0) \
            - pp.lgamma(value + 1.0).sum(axis=-1)
        return coeff + (value * pp.log(p)).sum(axis=-1)


class Geometric(Distribution):
    """P(X=k) = (1-p)^(k-1) p for k = 1, 2, ...
    (reference geometric.py:70,126)."""

    def __init__(self, probs):
        self.probs = _as_tensor(probs)
        super().__init__(batch_shape=tuple(self.probs.shape))

    @property
    def mean(self):
        return 1.0 / self.probs

    @property
    def variance(self):
        return (1.0 - self.probs) / (self.probs * self.probs)

    def pmf(self, k):
        k = _as_tensor(k)
        return pp.pow(1.0 - self.probs, k - 1.0) * self.probs

    def log_pmf(self, k):
        k = _as_tensor(k)
        p = _clip_prob(self.probs)
        return (k - 1.0) * pp.log1p(-p) + pp.log(p)

    def log_prob(self, value):
        return self.log_pmf(value)

    def sample(self, shape=()):
        out_shape = self._extend_shape(tuple(shape))
        u = jax.random.uniform(_state.next_key(), out_shape, jnp.float32,
                               minval=_EPS, maxval=1.0 - _EPS)
        p = jnp.broadcast_to(_clip_prob(self.probs)._data, out_shape)
        k = jnp.floor(jnp.log(u) / jnp.log1p(-p)) + 1.0
        return wrap_like(k)

    def entropy(self):
        p = _clip_prob(self.probs)
        q = 1.0 - p
        return -(q * pp.log(q) + p * pp.log(p)) / p

    def cdf(self, k):
        k = _as_tensor(k)
        p = _clip_prob(self.probs)
        return 1.0 - pp.pow(1.0 - p, k)
