"""Uniform, Laplace, Gumbel, Cauchy.

Parity: reference python/paddle/distribution/{uniform,laplace,gumbel,
cauchy}.py.  All rsamples are inverse-CDF reparameterizations: a raw
uniform draw is the constant, the parameter math is taped.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.core import state as _state
from paddle_tpu.core.dispatch import wrap_like
from paddle_tpu.distribution.distribution import (Distribution, _as_tensor,
                                                  _broadcast_shape)

__all__ = ["Uniform", "Laplace", "Gumbel", "Cauchy"]

_EULER = 0.5772156649015329


def _std_uniform(shape, lo=1e-7, hi=1.0 - 1e-7):
    return wrap_like(jax.random.uniform(_state.next_key(), shape,
                                        jnp.float32, minval=lo, maxval=hi))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)
        super().__init__(batch_shape=_broadcast_shape(self.low, self.high))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12.0

    def rsample(self, shape=()):
        u = _std_uniform(self._extend_shape(tuple(shape)), lo=0.0, hi=1.0)
        return self.low + (self.high - self.low) * u

    def entropy(self):
        return pp.log(self.high - self.low)

    def log_prob(self, value):
        value = _as_tensor(value)
        inside = pp.logical_and(value >= self.low, value < self.high)
        lp = -pp.log(self.high - self.low)
        neg_inf = pp.full_like(value * lp, -float("inf"))
        return pp.where(inside, value * 0.0 + lp, neg_inf)

    def cdf(self, value):
        value = _as_tensor(value)
        return pp.clip((value - self.low) / (self.high - self.low), 0.0, 1.0)


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(batch_shape=_broadcast_shape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    @property
    def stddev(self):
        return math.sqrt(2.0) * self.scale

    def rsample(self, shape=()):
        u = _std_uniform(self._extend_shape(tuple(shape))) - 0.5
        return self.loc - self.scale * pp.sign(u) * pp.log1p(-2.0 * pp.abs(u))

    def entropy(self):
        return 1.0 + pp.log(2.0 * self.scale)

    def log_prob(self, value):
        value = _as_tensor(value)
        return -pp.log(2.0 * self.scale) - pp.abs(value - self.loc) / self.scale

    def cdf(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * pp.sign(z) * pp.expm1(-pp.abs(z))

    def icdf(self, value):
        value = _as_tensor(value)
        term = value - 0.5
        return self.loc - self.scale * pp.sign(term) * pp.log1p(
            -2.0 * pp.abs(term))


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(batch_shape=_broadcast_shape(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc + _EULER * self.scale

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale * self.scale

    def rsample(self, shape=()):
        u = _std_uniform(self._extend_shape(tuple(shape)))
        return self.loc - self.scale * pp.log(-pp.log(u))

    def entropy(self):
        return pp.log(self.scale) + 1.0 + _EULER

    def log_prob(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return -(z + pp.exp(-z)) - pp.log(self.scale)

    def cdf(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return pp.exp(-pp.exp(-z))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(batch_shape=_broadcast_shape(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    def rsample(self, shape=()):
        u = _std_uniform(self._extend_shape(tuple(shape)))
        return self.loc + self.scale * pp.tan(math.pi * (u - 0.5))

    def entropy(self):
        return pp.log(4.0 * math.pi * self.scale)

    def log_prob(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return -math.log(math.pi) - pp.log(self.scale) - pp.log1p(z * z)

    def cdf(self, value):
        value = _as_tensor(value)
        z = (value - self.loc) / self.scale
        return pp.atan(z) / math.pi + 0.5
