"""Device management (parity: python/paddle/device/).

TPU-native: one logical backend (XLA). set_device accepts 'tpu'/'cpu'/'gpu'
spellings; device queries map to jax.devices()."""

from __future__ import annotations

import jax

_current = None


def set_device(device: str):
    global _current
    _current = device
    return device


def get_device() -> str:
    if _current is not None:
        return _current
    backend = jax.default_backend()
    return f"{backend}:0"


def get_all_custom_device_type():
    return ["tpu"] if jax.default_backend() == "tpu" else []


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return jax.default_backend() == "tpu"


class Stream:
    """Parity shim: XLA owns stream scheduling on TPU; we expose the API shape
    (reference: python/paddle/device/cuda/streams.py) as ordered no-ops."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        for d in jax.devices():
            pass

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        pass


def synchronize(device=None):
    """Block until all queued work on the device is complete."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


def current_stream(device=None):
    return Stream(device)


cuda = None  # no CUDA in the build, by design (BASELINE.md constraint)
