"""Device management (parity: python/paddle/device/).

TPU-native: one logical backend (XLA). set_device accepts 'tpu'/'cpu'/'gpu'
spellings; device queries map to jax.devices()."""

from __future__ import annotations

import jax

_current = None


def set_device(device: str):
    global _current
    _current = device
    return device


def get_device() -> str:
    if _current is not None:
        return _current
    backend = jax.default_backend()
    return f"{backend}:0"


def get_all_custom_device_type():
    return ["tpu"] if jax.default_backend() == "tpu" else []


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_tpu():
    return jax.default_backend() == "tpu"


class Stream:
    """Parity shim: XLA owns stream scheduling on TPU; we expose the API shape
    (reference: python/paddle/device/cuda/streams.py) as ordered no-ops."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        for d in jax.devices():
            pass

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        pass


def synchronize(device=None):
    """Block until all queued work on the device is complete."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


def current_stream(device=None):
    return Stream(device)


# -- memory statistics (reference: paddle.device.cuda.memory_allocated /
# platform/monitor.cc + memory/stats.cc counters).  TPU-native: XLA/PJRT
# owns allocation; per-device stats surface through Device.memory_stats().

def memory_stats(device=None) -> dict:
    """Raw PJRT allocator counters for one device ({} when the backend
    does not expose them, e.g. tunneled/experimental platforms)."""
    devs = jax.devices()
    idx = 0
    if isinstance(device, int):
        idx = device
    elif isinstance(device, str) and ":" in device:
        idx = int(device.rsplit(":", 1)[1])
    stats = devs[idx].memory_stats()
    return dict(stats) if stats else {}


def memory_allocated(device=None) -> int:
    """Bytes currently held by live buffers on the device."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """High-water mark of live-buffer bytes."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes the allocator has reserved from the device (pool size)."""
    s = memory_stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_reserved", s.get("peak_bytes_in_use", 0)))


def device_memory_limit(device=None) -> int:
    """Total memory the allocator may use (HBM capacity budget)."""
    return int(memory_stats(device).get("bytes_limit", 0))


class _CudaNamespace:
    """paddle.device.cuda parity veneer over the XLA stats — the reference
    API names kept so monitoring code ports unchanged (no CUDA exists in
    this build; numbers are the accelerator's)."""
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)

    @staticmethod
    def empty_cache():
        pass  # XLA manages its pools; nothing to drop

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize(device)


cuda = _CudaNamespace()
