"""Process-global framework state: default dtype, global seed / RNG stream.

The reference keeps the global generator per device (paddle.seed fans out,
python/paddle/framework/random.py).  JAX RNG is functional; for the eager API
we keep a mutable key that splits on every draw — the jitted training path
threads keys explicitly instead (idiomatic jax)."""

from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
_default_dtype = "float32"
# Lazy: creating a key initializes the XLA backend, which must not happen
# at import time — multi-controller users need `import paddle_tpu` →
# `distributed.init_parallel_env()` (jax.distributed.initialize) to run
# BEFORE any backend-touching call.
_key = None
_seed = 0


def set_default_dtype(dtype: str):
    global _default_dtype
    from paddle_tpu.core import dtypes
    if isinstance(dtype, str):
        name = dtype.replace("paddle.", "")
    else:
        name = dtypes.from_jax(dtype)
    if name not in dtypes.FLOATING:
        raise ValueError(f"default dtype must be floating, got {dtype}")
    _default_dtype = name


def get_default_dtype() -> str:
    return _default_dtype


def seed(s: int):
    global _key, _seed
    with _lock:
        _seed = int(s)
        _key = jax.random.key(_seed)
    return _seed


def get_seed() -> int:
    return _seed


def next_key():
    """Split the global eager key and return a fresh subkey."""
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.key(_seed)
        _key, sub = jax.random.split(_key)
    return sub


def derive_seed() -> int:
    """A fresh host-side integer seed drawn from the global RNG stream —
    deterministic under ``seed()``, different on every call.  Host-side
    consumers (data shuffling, worker seeding) hang off this instead of
    OS entropy so a seeded run shuffles reproducibly."""
    import numpy as np
    return int(np.asarray(
        jax.random.randint(next_key(), (), 0, np.iinfo(np.int32).max)))


def get_rng_state():
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.key(_seed)
    return jax.random.key_data(_key)


def set_rng_state(data):
    global _key
    with _lock:
        _key = jax.random.wrap_key_data(data)
