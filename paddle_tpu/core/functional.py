"""functional_call: run an eagerly-defined Layer as a pure function.

This is the bridge between the paddle-style imperative Layer API and JAX's
functional transforms — the TPU-native answer to the reference's dy2static
(@to_static AST rewriting, python/paddle/jit/dy2static/program_translator.py:305).
Instead of rewriting Python source, we swap every Parameter/buffer access for
a traced value through a context-local substitution map; ops called on raw
traced values bypass the eager tape entirely (core/dispatch.py), so tracing a
Layer's __call__ yields exactly the jaxpr a hand-written pure function would.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, Optional

import jax

_SUBST: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "param_substitution", default=None)
_RNG: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "functional_rng", default=None)


def substitution_active() -> bool:
    return _SUBST.get() is not None


def lookup(tensor):
    """Return the substituted traced value for an eager Tensor, or None."""
    m = _SUBST.get()
    if m is None:
        return None
    return m.get(id(tensor))


@contextlib.contextmanager
def substitute(mapping: Dict[int, Any], rngs: Optional[Dict[str, Any]] = None):
    tok = _SUBST.set(mapping)
    rng_state = {k: [v, 0] for k, v in (rngs or {}).items()}
    tok2 = _RNG.set(rng_state)
    try:
        yield
    finally:
        _SUBST.reset(tok)
        _RNG.reset(tok2)


def functional_rng_active() -> bool:
    return _RNG.get() is not None and len(_RNG.get()) > 0


def next_functional_key(stream: str = "dropout"):
    """Trace-safe RNG: fold an incrementing counter into the stream key."""
    st = _RNG.get()
    if not st or stream not in st:
        return None
    entry = st[stream]
    key = jax.random.fold_in(entry[0], entry[1])
    entry[1] += 1
    return key


def functional_call(layer, params_and_buffers: Dict[str, Any], *args,
                    rngs: Optional[Dict[str, Any]] = None,
                    method: Optional[str] = None, **kwargs):
    """Call `layer` with its parameters/buffers replaced by the values in
    `params_and_buffers` (a dict keyed like state_dict(), values raw jax
    arrays or Tensors).  Safe to use inside jax.jit/grad/vmap.
    ``method`` selects a bound method other than forward/__call__
    (e.g. a model's ``loss``).
    """
    from paddle_tpu.core.tensor import Tensor

    state = layer.state_dict(keep_vars=True)
    mapping = {}
    for name, value in params_and_buffers.items():
        if name not in state:
            raise KeyError(f"unknown parameter/buffer '{name}' for "
                           f"{type(layer).__name__}")
        v = value._data if isinstance(value, Tensor) else value
        mapping[id(state[name])] = v
    fn = layer if method is None else getattr(layer, method)
    with substitute(mapping, rngs):
        return fn(*args, **kwargs)


def params_of(layer, dtype=None):
    """Extract {name: jax.Array} of all params+buffers — the pytree that
    functional_call/grad operate on."""
    out = {}
    for name, t in layer.state_dict(keep_vars=True).items():
        arr = t._data
        if dtype is not None:
            import jax.numpy as jnp
            if jnp.issubdtype(arr.dtype, jnp.floating):
                arr = arr.astype(dtype)
        out[name] = arr
    return out


def trainable_mask(layer):
    """{name: bool} — True for trainable parameters (not buffers, not frozen)."""
    from paddle_tpu.core.tensor import Parameter
    mask = {}
    for name, t in layer.state_dict(keep_vars=True).items():
        mask[name] = isinstance(t, Parameter) and not t.stop_gradient
    return mask
