"""Attach the op surface onto Tensor as methods/dunders.

The reference generates Tensor methods from the YAML op registry
(python/paddle/tensor/__init__.py tensor_method_func list + monkey-patching in
python/paddle/framework/framework.py).  We do the same in one place: a single
table mapping method name → op function, applied at import."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import dispatch
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import (creation, linalg, logic, manipulation, math,
                            search, stat)


def _binop(fn, reverse=False):
    def op(self, other):
        if reverse:
            return fn(other, self)
        return fn(self, other)
    return op


_DUNDERS = {
    "__add__": _binop(math.add),
    "__radd__": _binop(math.add, True),
    "__sub__": _binop(math.subtract),
    "__rsub__": _binop(math.subtract, True),
    "__mul__": _binop(math.multiply),
    "__rmul__": _binop(math.multiply, True),
    "__truediv__": _binop(math.divide),
    "__rtruediv__": _binop(math.divide, True),
    "__floordiv__": _binop(math.floor_divide),
    "__rfloordiv__": _binop(math.floor_divide, True),
    "__mod__": _binop(math.remainder),
    "__rmod__": _binop(math.remainder, True),
    "__pow__": _binop(math.pow),
    "__rpow__": _binop(math.pow, True),
    "__matmul__": _binop(linalg.matmul),
    "__rmatmul__": _binop(linalg.matmul, True),
    "__neg__": lambda self: math.neg(self),
    "__abs__": lambda self: math.abs(self),
    "__invert__": lambda self: logic.logical_not(self) if self.dtype == "bool"
                  else logic.bitwise_not(self),
    "__eq__": _binop(logic.equal),
    "__ne__": _binop(logic.not_equal),
    "__lt__": _binop(logic.less_than),
    "__le__": _binop(logic.less_equal),
    "__gt__": _binop(logic.greater_than),
    "__ge__": _binop(logic.greater_equal),
    "__and__": lambda s, o: logic.logical_and(s, o) if s.dtype == "bool"
               else logic.bitwise_and(s, o),
    "__or__": lambda s, o: logic.logical_or(s, o) if s.dtype == "bool"
              else logic.bitwise_or(s, o),
    "__xor__": lambda s, o: logic.logical_xor(s, o) if s.dtype == "bool"
               else logic.bitwise_xor(s, o),
    "__lshift__": _binop(logic.bitwise_left_shift),
    "__rshift__": _binop(logic.bitwise_right_shift),
}

_METHOD_SOURCES = [math, linalg, manipulation, logic, search, stat]

# names that clash with Tensor internals or builtins we must not override
_SKIP = {"is_tensor", "where"}

_EXTRA_METHODS = {
    "zeros_like": creation.zeros_like,
    "ones_like": creation.ones_like,
    "full_like": creation.full_like,
    "tril": creation.tril,
    "triu": creation.triu,
    "diag": creation.diag,
    "where": manipulation.where,
}


def _install():
    for name, fn in _DUNDERS.items():
        setattr(Tensor, name, fn)
    for mod in _METHOD_SOURCES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or hasattr(Tensor, name):
                continue
            setattr(Tensor, name, fn)
    for name, fn in _EXTRA_METHODS.items():
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    # reductions with paddle method-style defaults already match fn signatures
    Tensor.dim = lambda self: self.ndim
    Tensor.rank = lambda self: self.ndim
    Tensor.element_size = lambda self: jnp.dtype(self._data.dtype).itemsize
    # Tensor.T property (python/paddle/tensor/attribute.py role): reverse
    # ALL dims — paddle semantics, unlike numpy's 2-d-only convention
    Tensor.T = property(lambda self: manipulation.transpose(
        self, list(range(self.ndim))[::-1]))
    Tensor.mT = property(_mT)


def _mT(self):
    if self.ndim < 2:
        raise ValueError(
            f"Tensor.mT needs ndim >= 2, got shape {self.shape}")
    return manipulation.transpose(
        self, list(range(self.ndim - 2)) + [self.ndim - 1, self.ndim - 2])


_install()
