"""Eager Tensor facade over jax.Array with a tape-based autograd engine.

Design (TPU-native rethink of the reference's eager mode):
  * The reference (Paddle) implements eager autograd as generated C++ GradNode
    classes per op (/root/reference/paddle/fluid/eager/, grad_node_info.h:168,
    backward.cc:104).  Re-deriving per-op VJPs by hand would duplicate what JAX
    already provides, so here every differentiable eager op call is routed
    through ``jax.vjp`` once and the returned pullback is recorded on a tape
    (`GradNode`).  ``Tensor.backward()`` then walks the tape exactly like the
    reference's ``RunBackward`` queue.
  * Inside ``jax.jit`` traces there are no Tensors at all: the same op
    implementations run directly on traced jax values (see
    paddle_tpu/core/dispatch.py), so the compiled path pays zero overhead for
    the eager machinery.  This is the dygraph/static duality of the reference
    collapsed onto one code path.

Semantics parity notes:
  * ``stop_gradient`` defaults to True for ad-hoc tensors (matching
    paddle.to_tensor) and False for ``Parameter``.
  * ``.grad`` accumulates across ``backward()`` calls until ``clear_grad()``.
  * In-place mutation of a tensor that another node saved for backward uses the
    *saved* (old) value: jax arrays are immutable, so the tape closure holds
    the pre-mutation value.  The reference aborts in this case via
    inplace_version checks (eager/tensor_wrapper.h); we track versions and
    raise on backward when detected.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtypes as _dtypes

__all__ = [
    "Tensor",
    "Parameter",
    "GradNode",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
]


class _AutogradState(threading.local):
    def __init__(self):
        self.grad_enabled = True


_STATE = _AutogradState()


def is_grad_enabled() -> bool:
    return _STATE.grad_enabled


def set_grad_enabled(mode: bool):
    _STATE.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad():
    prev = _STATE.grad_enabled
    _STATE.grad_enabled = False
    try:
        yield
    finally:
        _STATE.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _STATE.grad_enabled
    _STATE.grad_enabled = True
    try:
        yield
    finally:
        _STATE.grad_enabled = prev


class GradNode:
    """One tape entry: the pullback of a single eager op call.

    Mirrors the role of the reference's GradNodeBase
    (paddle/fluid/eager/grad_node_info.h:168) but the gradient function is the
    jax.vjp pullback instead of a hand-written grad kernel.
    """

    __slots__ = (
        "vjp_fn",
        "inputs",
        "input_versions",
        "out_avals",
        "out_treedef",
        "n_outputs",
        "name",
        "create_graph_apply",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, out_treedef, name=""):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor] — the differentiable inputs
        self.input_versions = [t._version for t in inputs]
        self.out_avals = out_avals  # list[(shape, dtype)] flat over outputs
        self.out_treedef = out_treedef
        self.n_outputs = len(out_avals)
        self.name = name
        # Optional taped double-backward: list[Tensor|None] -> list[Tensor|None].
        # Set by the dispatcher (re-entrant jax.vjp over the op closure) and by
        # PyLayer (user backward under enable_grad); used by
        # grad(create_graph=True) so grads themselves carry grad history.
        self.create_graph_apply = None

    def apply(self, cotangents):
        """cotangents: flat list aligned with out_avals (None → zeros)."""
        if self.vjp_fn is None:
            raise RuntimeError(
                f"Trying to run backward through '{self.name}' a second time, "
                "but the saved intermediate results have already been freed. "
                "Specify retain_graph=True on the first backward() if you "
                "need to backward through the graph again.")
        filled = [
            c if c is not None else jnp.zeros(shape, dtype)
            for c, (shape, dtype) in zip(cotangents, self.out_avals)
        ]
        cot_tree = jax.tree.unflatten(self.out_treedef, filled)
        for t, v in zip(self.inputs, self.input_versions):
            if t._version != v:
                raise RuntimeError(
                    f"Tensor saved for backward of '{self.name}' was modified "
                    f"in-place (version {v} -> {t._version}). Clone it before "
                    "mutating, or avoid in-place ops on tensors needed for grad."
                )
        return self.vjp_fn(cot_tree)

    def __repr__(self):
        return f"<GradNode {self.name} n_in={len(self.inputs)} n_out={self.n_outputs}>"


def _as_jax_array(data, dtype=None):
    if isinstance(data, Tensor):
        arr = data._data
        if dtype is not None:
            arr = arr.astype(_dtypes.to_jax(dtype))
        return arr
    if isinstance(data, (jnp.ndarray, jax.Array)):
        return data if dtype is None else data.astype(_dtypes.to_jax(dtype))
    if isinstance(data, np.ndarray):
        if dtype is None and data.dtype == np.float64:
            data = data.astype(np.float32)
        return jnp.asarray(data, dtype=None if dtype is None else _dtypes.to_jax(dtype))
    if isinstance(data, (bool, int, float, complex, list, tuple, np.generic)):
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return jnp.asarray(arr, dtype=None if dtype is None else _dtypes.to_jax(dtype))
    raise TypeError(f"Cannot convert {type(data)} to Tensor")


class Tensor:
    """Paddle-flavoured eager tensor wrapping an immutable jax.Array."""

    __slots__ = ("_data", "stop_gradient", "_grad", "_grad_node", "_out_index",
                 "_version", "name", "persistable", "_retain_grads",
                 "partition_spec", "__weakref__")

    # let Tensor win in  np_array op tensor  reflected dispatch
    __array_priority__ = 100

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        self._data = _as_jax_array(data, dtype)
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._grad_node: Optional[GradNode] = None
        self._out_index: int = 0
        self._version = 0
        self.name = name
        self.persistable = False
        self._retain_grads = False

    # -- construction helpers ------------------------------------------------
    @staticmethod
    def _wrap(arr, stop_gradient=True, node=None, out_index=0):
        t = Tensor.__new__(Tensor)
        t._data = arr
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = node
        t._out_index = out_index
        t._version = 0
        t.name = None
        t.persistable = False
        t._retain_grads = False
        return t

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return _dtypes.from_jax(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        if devs is None:
            return "unknown"
        return str(next(iter(self._data.devices())))

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return int(self._data.size)

    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __len__(self):
        if self._data.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_info},\n"
                f"       {np.asarray(self._data)!r})")

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __index__(self):
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a if dtype is None else a.astype(dtype)

    def __jax_array__(self):
        # lets raw jnp ops consume Tensors transparently (no grad tracking!)
        return self._data

    # -- grad machinery ------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self):  # paddle alias
        self._grad = None

    def retain_grads(self):
        self._retain_grads = True

    def backward(self, grad_tensor=None, retain_graph=False):
        from paddle_tpu.autograd.backward_engine import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def detach(self):
        return Tensor._wrap(self._data, stop_gradient=True)

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from paddle_tpu.core.dispatch import dispatch
        return dispatch(lambda x: x + jnp.zeros((), x.dtype), self, op_name="clone")

    # -- dtype / device ------------------------------------------------------
    def astype(self, dtype):
        from paddle_tpu.core.dispatch import dispatch
        jdt = _dtypes.to_jax(dtype)
        return dispatch(lambda x: x.astype(jdt), self, op_name="cast")

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # device moves are no-ops (single logical device per process); dtype honoured
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in _dtypes.ALL_DTYPE_NAMES:
                return self.astype(a)
            if hasattr(a, "dtype") or str(a) in _dtypes.ALL_DTYPE_NAMES:
                try:
                    return self.astype(a)
                except Exception:
                    pass
        return self

    def cpu(self):
        return Tensor._wrap(self._data, stop_gradient=self.stop_gradient)

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # -- in-place ------------------------------------------------------------
    def _set_data(self, arr):
        """Raw in-place value replacement (version-bumping)."""
        self._data = arr
        self._version += 1

    def set_value(self, value):
        arr = _as_jax_array(value)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._data.shape}")
        self._set_data(arr.astype(self._data.dtype))

    def copy_(self, other, *a):
        self.set_value(other)
        return self

    def fill_(self, value):
        self._set_data(jnp.full_like(self._data, value))
        return self

    def zero_(self):
        self._set_data(jnp.zeros_like(self._data))
        return self

    def scale_(self, scale=1.0, bias=0.0):
        self._set_data(self._data * scale + bias)
        return self

    def add_(self, y):
        self._set_data(self._data + _as_jax_array(y).astype(self._data.dtype))
        return self

    def subtract_(self, y):
        self._set_data(self._data - _as_jax_array(y).astype(self._data.dtype))
        return self

    def multiply_(self, y):
        self._set_data(self._data * _as_jax_array(y).astype(self._data.dtype))
        return self

    def clip_(self, min=None, max=None):
        self._set_data(jnp.clip(self._data, min, max))
        return self

    # -- indexing ------------------------------------------------------------
    def _normalize_index(self, idx):
        def conv(i):
            if isinstance(i, Tensor):
                return i._data
            return i
        if isinstance(idx, tuple):
            return tuple(conv(i) for i in idx)
        return conv(idx)

    def __getitem__(self, idx):
        from paddle_tpu.core.dispatch import dispatch
        nidx = self._normalize_index(idx)
        return dispatch(lambda x: x[nidx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        nidx = self._normalize_index(idx)
        val = _as_jax_array(value)
        self._set_data(self._data.at[nidx].set(val.astype(self._data.dtype)))

    # NOTE: arithmetic dunders are attached in paddle_tpu/core/tensor_methods.py
    # (generated from the op table) to keep this file focused on the engine.


class Parameter(Tensor):
    """A trainable Tensor (stop_gradient=False by default), as registered by
    nn.Layer — parity with paddle's EagerParamBase."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, dtype=None, trainable=True, name=None):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.persistable = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
