"""Row-sparse gradients — the SelectedRows analog for embedding-scale params.

Reference parity: ``paddle/phi/core/selected_rows.h`` (a {rows, value,
height} triple used as the gradient type of ``embedding(sparse=True)``)
plus the sparse-kernel family under ``phi/kernels/selected_rows/``
(sgd/adam updates proportional to touched rows, ~3.5k LoC).

TPU-native: the triple is two arrays — ``rows`` [N] int32 and ``values``
[N, d] — and every consumer is a gather/scatter the TPU executes natively:
  * accumulation  = concatenation (no densification),
  * optimizer update = ``param.at[rows].add/...`` on the donated buffer,
  * lazy Adam     = moment gather → rule → scatter, rows-touched only.
A [vocab, d] dense gradient is never materialized anywhere on the path.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = ["RowSparseGrad"]


class RowSparseGrad:
    """Gradient of shape `shape` that is zero outside `rows`.

    ``rows`` may contain duplicates (the same token appearing twice in a
    batch); semantics are scatter-ADD.  ``coalesce()`` returns an
    equivalent grad with unique rows (summed values) — optimizer moment
    updates need that form, plain SGD scatter-adds don't.
    """

    def __init__(self, rows, values, shape: Tuple[int, ...],
                 coalesced: bool = False):
        self.rows = jnp.asarray(rows, dtype=jnp.int32)
        self.values = values
        self.shape = tuple(shape)
        self.coalesced = coalesced  # rows known unique → coalesce() no-ops
        if self.values.shape[1:] != self.shape[1:]:
            raise ValueError(
                f"values trailing dims {self.values.shape[1:]} != dense "
                f"trailing dims {self.shape[1:]}")

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz_rows(self) -> int:
        return int(self.rows.shape[0])

    def to_dense(self):
        return jnp.zeros(self.shape, self.values.dtype).at[self.rows].add(
            self.values)

    def coalesce(self) -> "RowSparseGrad":
        """Unique rows with summed values (eager-only: output shape is
        data-dependent).  Idempotent: a grad already marked coalesced is
        returned as-is (clip coalesces, the optimizer must not re-pay)."""
        if self.coalesced:
            return self
        uniq, inv = jnp.unique(self.rows, return_inverse=True)
        summed = jnp.zeros((uniq.shape[0],) + self.values.shape[1:],
                           self.values.dtype).at[inv].add(self.values)
        return RowSparseGrad(uniq, summed, self.shape, coalesced=True)

    def scale(self, s) -> "RowSparseGrad":
        return RowSparseGrad(self.rows, self.values * s, self.shape,
                             coalesced=self.coalesced)

    def astype(self, dtype) -> "RowSparseGrad":
        return RowSparseGrad(self.rows, self.values.astype(dtype),
                             self.shape, coalesced=self.coalesced)

    def __add__(self, other):
        if isinstance(other, RowSparseGrad):
            if other.shape != self.shape:
                raise ValueError(f"shape mismatch {self.shape} vs "
                                 f"{other.shape}")
            return RowSparseGrad(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.shape)
        # sparse + dense → dense (mixed consumers forced an upgrade)
        arr = other._data if hasattr(other, "_data") else jnp.asarray(other)
        return self.to_dense() + arr

    __radd__ = __add__

    def __repr__(self):
        return (f"RowSparseGrad(shape={self.shape}, "
                f"nnz_rows={self.nnz_rows}, dtype={self.dtype})")
