"""Dual-mode op dispatch: every op is ONE pure jax function.

  * Called with raw jax values (inside jit / vmap / grad traces) it runs
    directly — zero overhead, fully fusible by XLA.
  * Called with eager ``Tensor`` objects it routes through ``dispatch``: the
    differentiable float inputs become jax.vjp primals, the pullback lands on
    the tape (core/tensor.py:GradNode).

This replaces the reference's four generated layers (C++ API / ad_func /
GradNode / pybind _C_ops — see SURVEY.md §3.1) with a single Python dispatcher,
because XLA + jax.vjp supply kernel selection and per-op gradients for free.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes as _dtypes
from paddle_tpu.core.tensor import GradNode, Tensor, is_grad_enabled

__all__ = ["dispatch", "eager_op", "unwrap", "wrap_like"]


def unwrap(x):
    if isinstance(x, Tensor):
        from paddle_tpu.core import functional as _func
        sub = _func.lookup(x)
        return x._data if sub is None else sub
    return x


def _tree_unwrap(tree):
    return jax.tree.map(unwrap, tree, is_leaf=lambda x: isinstance(x, Tensor))


def wrap_like(arr, stop_gradient=True):
    return Tensor._wrap(arr, stop_gradient=stop_gradient)


def _collect_tensors(tree):
    out = []
    jax.tree.map(lambda x: out.append(x) if isinstance(x, Tensor) else None,
                 tree, is_leaf=lambda x: isinstance(x, Tensor))
    return out


def _amp_wrap(fn, op_name):
    """Wrap fn so float array args are cast per the active AMP policy."""
    from paddle_tpu import amp as _amp
    if not _amp.is_auto_cast_enabled():
        return fn

    def wrapped(*a, **kw):
        leaves, treedef = jax.tree.flatten((a, kw))
        leaves = _amp.maybe_cast_args(op_name, leaves)
        ra, rkw = jax.tree.unflatten(treedef, leaves)
        return fn(*ra, **rkw)

    return wrapped


def _check_nan_inf(op_name: str, out):
    """FLAGS_check_nan_inf sweep (reference: per-op output scan,
    framework/details/nan_inf_utils_detail.cc:26 + eager hook
    eager/nan_inf_utils.cc).  Eager-mode debugging aid — forces a device
    sync per op, exactly like the reference's blocking check."""
    from paddle_tpu import flags as _flags
    try:
        if not _flags.get("check_nan_inf"):
            return
    except KeyError:
        return
    level = _flags.get("check_nan_inf_level")
    for leaf in jax.tree.leaves(out):
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if hasattr(arr, "dtype") and jnp.issubdtype(arr.dtype, jnp.floating):
            bad = int(jnp.logical_not(jnp.isfinite(arr)).sum())
            if bad:
                msg = (f"[check_nan_inf] op '{op_name}' produced {bad} "
                       f"non-finite values (shape {arr.shape}, "
                       f"dtype {arr.dtype})")
                if level == 0:
                    raise FloatingPointError(msg)
                print(msg)


def dispatch(fn: Callable, *args, op_name: str = "", **kwargs):
    """Run pure fn over (args, kwargs); handle Tensor inputs + tape recording.

    fn receives raw jax values in place of Tensors.
    Returns Tensors if any input was a Tensor, else fn's raw result.
    """
    fn = _amp_wrap(fn, op_name)
    from paddle_tpu.amp import debugging as _dbg
    _dbg.record_op(op_name)
    tensors = _collect_tensors((args, kwargs))
    if not tensors:
        return fn(*args, **kwargs)

    from paddle_tpu.core import functional as _func
    if _func.substitution_active():
        # functional (traced) mode: all Tensors resolve through the
        # substitution map; no tape, no wrapping — pure jax values out.
        rargs, rkwargs = _tree_unwrap((args, kwargs))
        return fn(*rargs, **rkwargs)

    diff = [t for t in tensors
            if not t.stop_gradient and _dtypes.is_floating(t._data.dtype)]
    if not (is_grad_enabled() and diff):
        rargs, rkwargs = _tree_unwrap((args, kwargs))
        out = fn(*rargs, **rkwargs)
        _check_nan_inf(op_name, out)
        return jax.tree.map(wrap_like, out)

    # Substitute primal placeholders for the differentiable tensors; close over
    # everything else.  id()-keyed because the same Tensor may appear twice.
    diff_ids = {}
    primal_list = []
    for t in diff:
        if id(t) not in diff_ids:
            diff_ids[id(t)] = len(primal_list)
            primal_list.append(t._data)
    uniq_diff = [None] * len(primal_list)
    for t in diff:
        uniq_diff[diff_ids[id(t)]] = t

    def sub(x, primals):
        if isinstance(x, Tensor):
            i = diff_ids.get(id(x))
            return x._data if i is None else primals[i]
        return x

    def closure(*primals):
        rargs, rkwargs = jax.tree.map(
            lambda x: sub(x, primals), (args, kwargs),
            is_leaf=lambda x: isinstance(x, Tensor))
        return fn(*rargs, **rkwargs)

    out, vjp_fn = jax.vjp(closure, *primal_list)
    _check_nan_inf(op_name, out)

    flat_out, treedef = jax.tree.flatten(out)
    avals = [(o.shape, o.dtype) for o in flat_out]
    node = GradNode(vjp_fn, uniq_diff, avals, treedef,
                    name=op_name or getattr(fn, "__name__", "op"))

    def _cg_apply(cot_flat, _avals=avals, _treedef=treedef,
                  _closure=closure, _inputs=uniq_diff, _name=op_name):
        """Taped double-backward: re-enter jax.vjp over the op closure so the
        produced grads are themselves tape-recorded (create_graph=True)."""
        filled = [c if c is not None else jnp.zeros(s, d)
                  for c, (s, d) in zip(cot_flat, _avals)]

        def double_fn(cots, *primals):
            cot_tree = jax.tree.unflatten(_treedef, list(cots))
            _, vjp = jax.vjp(_closure, *primals)
            return tuple(vjp(cot_tree))

        out = dispatch(double_fn, tuple(filled), *_inputs,
                       op_name=f"{_name or 'op'}_grad")
        return list(out) if isinstance(out, (tuple, list)) else [out]

    node.create_graph_apply = _cg_apply
    wrapped = []
    for i, o in enumerate(flat_out):
        sg = not _dtypes.is_floating(o.dtype)
        t = Tensor._wrap(o, stop_gradient=sg,
                         node=None if sg else node, out_index=i)
        wrapped.append(t)
    return jax.tree.unflatten(treedef, wrapped)


def eager_op(fn: Callable = None, *, name: str = None,
             factory: bool = False):
    """Decorator: make a pure-jax op callable with Tensors (tape-aware) or raw
    jax values (direct). ``name=`` kwarg of the op itself (paddle API parity)
    is swallowed before dispatch.

    ``factory=True`` marks tensor FACTORIES (zeros/ones/arange/... — no
    tensor inputs): in eager context their outputs wrap into Tensors
    (paddle parity: ``paddle.ones`` returns a Tensor), while traced
    callers still get raw values."""

    def deco(f):
        opname = name or f.__name__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            kwargs.pop("name", None)
            out = dispatch(f, *args, op_name=opname, **kwargs)
            if factory:
                from paddle_tpu.core import functional as _func
                leaves = jax.tree.leaves(out)
                if not _func.substitution_active() and leaves and not any(
                        isinstance(v, jax.core.Tracer) for v in leaves):
                    out = jax.tree.map(wrap_like, out)
            return out

        wrapper.__wrapped_pure__ = f
        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
