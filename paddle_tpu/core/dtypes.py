"""Dtype table and conversions (parity with paddle dtype strings).

Reference: paddle/phi/common/data_type.h + python/paddle/framework/dtype.py.
On TPU the preferred compute dtype is bfloat16; float64 is supported by jax
only with x64 enabled, which we deliberately leave off (TPU-native default).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_NAME_TO_JAX = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

ALL_DTYPE_NAMES = frozenset(_NAME_TO_JAX)

FLOATING = frozenset({"float16", "bfloat16", "float32", "float64",
                      "float8_e4m3fn", "float8_e5m2"})
COMPLEX = frozenset({"complex64", "complex128"})
INTEGER = frozenset({"int8", "uint8", "int16", "int32", "int64",
                     "uint16", "uint32", "uint64"})

# Exposed as module-level dtype objects: paddle_tpu.float32 is the string name;
# simple and serializable, matching how users spell dtypes in paddle.
float32 = "float32"
float64 = "float64"
float16 = "float16"
bfloat16 = "bfloat16"
int8 = "int8"
uint8 = "uint8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
bool_ = "bool"
complex64 = "complex64"
complex128 = "complex128"


def to_jax(dtype):
    """Accept dtype name str / np dtype / jnp dtype → jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = dtype.replace("paddle.", "")
        if name in _NAME_TO_JAX:
            return _NAME_TO_JAX[name]
        raise ValueError(f"Unknown dtype: {dtype}")
    return jnp.dtype(dtype)


def from_jax(jdt) -> str:
    name = np.dtype(jdt).name if not hasattr(jdt, "name") else jdt.name
    if name == "bool":
        return "bool"
    return name


def is_floating(dtype) -> bool:
    if dtype is None:
        return False
    name = dtype if isinstance(dtype, str) else from_jax(dtype)
    return name in FLOATING or name in COMPLEX


def default_float_dtype() -> str:
    from paddle_tpu.core import state
    return state.get_default_dtype()
