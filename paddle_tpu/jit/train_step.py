"""TrainStep — the compiled training step.

TPU-native replacement for the reference's static-graph path: where Paddle
builds a ProgramDesc and runs it on InterpreterCore
(python/paddle/fluid/executor.py:1241 → new_executor/interpretercore.cc:188),
here the whole train step (forward + backward + optimizer update) is ONE
jitted pure function over (params, opt_state, batch) pytrees.  XLA is the
interpreter, scheduler, and memory planner.

Supports single-chip jit and sharded pjit: pass `mesh` + `param_specs` and
every pytree is placed with NamedSharding; XLA/GSPMD inserts the collectives
(grad psum for DP, mp allreduce for TP, …).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.functional import functional_call, params_of, \
    trainable_mask

__all__ = ["TrainStep", "CompiledStepBase"]


def _resolve_plan(shardings, mesh, param_specs, batch_spec):
    """Expand a ``shardings=`` argument — an AutoShardPlan or a plain
    ``{name → PartitionSpec}`` dict — into (mesh, param_specs,
    batch_spec), keeping any explicitly-passed value."""
    if hasattr(shardings, "param_specs"):        # AutoShardPlan duck type
        if getattr(shardings, "is_pipeline", False):
            raise ValueError(
                "autoshard plan has pp>1 — a pipeline layout targets "
                "distributed.PipelineTrainStep, not TrainStep")
        mesh = mesh if mesh is not None else shardings.jax_mesh()
        param_specs = param_specs if param_specs is not None \
            else dict(shardings.param_specs)
        batch_spec = batch_spec if batch_spec is not None \
            else shardings.batch_spec
        return mesh, param_specs, batch_spec
    if isinstance(shardings, dict):
        if mesh is None:
            for sh in shardings.values():
                m = getattr(sh, "mesh", None)
                if m is not None:
                    mesh = m
                    break
        specs = {n: getattr(sh, "spec", sh) for n, sh in shardings.items()}
        return mesh, (param_specs if param_specs is not None else specs), \
            batch_spec
    raise TypeError(f"shardings= expects an AutoShardPlan or a dict, "
                    f"got {type(shardings).__name__}")


@jax.custom_vjp
def _ordered_after(x, token):
    """``x`` pinned to issue after ``token`` via optimization_barrier —
    the link of the collective-overlap prefetch chain.  The barrier is a
    forward scheduling constraint only; 0.4.x has no differentiation
    rule for it, so the VJP passes the cotangent straight through (the
    backward's gather/reduce-scatter schedule is XLA's to pick)."""
    return jax.lax.optimization_barrier((x, token))[0]


def _ordered_after_fwd(x, token):
    return _ordered_after(x, token), token


def _ordered_after_bwd(token, g):
    return g, jax.tree.map(jnp.zeros_like, token)


_ordered_after.defvjp(_ordered_after_fwd, _ordered_after_bwd)


def _train_metrics():
    """Lazily created instruments on the default registry (shared by
    every TrainStep in the process — that is what an operator scrapes)."""
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "step": reg.histogram(
            "paddle_tpu_train_step_seconds",
            "wall time of one compiled train step (fwd+bwd+update)"),
        "steps": reg.counter("paddle_tpu_train_steps_total",
                             "train steps executed"),
        "tokens": reg.counter("paddle_tpu_train_tokens_total",
                              "tokens consumed by train steps"),
        "tps": reg.gauge("paddle_tpu_train_tokens_per_second",
                         "tokens/s of the most recent train step"),
        "loss": reg.gauge("paddle_tpu_train_loss",
                          "loss of the most recent train step"),
        "gnorm": reg.gauge("paddle_tpu_train_grad_norm",
                           "global gradient norm of the most recent "
                           "train step"),
        "recompiles": reg.counter(
            "paddle_tpu_train_recompiles_total",
            "novel call signatures after the first — each one is a "
            "silent retrace + XLA compile"),
        "accum": reg.histogram(
            "paddle_tpu_train_accum_microbatches",
            "microbatches accumulated per optimizer update",
            buckets=(1, 2, 4, 8, 16, 32, 64)),
        "skipped": reg.counter(
            "paddle_tpu_train_step_skipped_total",
            "optimizer updates skipped by the non-finite step-guard "
            "(params and optimizer state left unchanged)",
            labelnames=("reason",)),
        "mfu": reg.gauge(
            "paddle_tpu_train_mfu",
            "measured model-FLOPs utilisation of the most recent step "
            "(XLA executable FLOPs / step time / device peak; set once "
            "TrainStep.compile() has introspected the executable)"),
        # goodput accounting (fleet observability tentpole): wall time
        # of APPLIED updates vs. time burned on guard-discarded ones —
        # observability.goodput turns these into the goodput gauge
        "productive": reg.counter(
            "paddle_tpu_train_productive_seconds_total",
            "step wall seconds whose optimizer update was applied "
            "(the goodput numerator)"),
        "skipped_s": reg.counter(
            "paddle_tpu_train_skipped_seconds_total",
            "step wall seconds whose update the non-finite step-guard "
            "discarded (lost time, debited from goodput)"),
        "ema": reg.gauge(
            "paddle_tpu_train_step_ema_seconds",
            "EMA of step wall time — host-labeled after fleet "
            "federation, the series the straggler SLO rule compares "
            "against the fleet median"),
    }


class CompiledStepBase:
    """Shared plumbing for compiled training steps (``TrainStep`` and
    ``distributed.PipelineTrainStep``): sharded placement of params and
    optimizer state, the donated-jit call protocol, lr/scheduler wiring,
    and the checkpoint state_dict round-trip.  Subclasses build
    ``self._jitted`` with signature
    ``(params, opt_state, step_count, *step_args, lr) ->
    (loss, params, opt_state, step_count)`` — the loss slot may be any
    pytree the subclass's caller unpacks (TrainStep returns
    ``(loss, grad_norm, skip_code)`` there for the telemetry gauges and
    the non-finite step-guard)."""

    def _init_step_state(self, optimizer, params, param_sh=None):
        """Place params on their shardings and derive optimizer state
        (each state leaf shaped like its param inherits the sharding)."""
        self.optimizer = optimizer
        self._param_sh = param_sh
        # copy defensively: the step donates its buffers to XLA, and
        # device_put may ALIAS the caller's array when the sharding already
        # matches — donation would silently delete the caller's copy
        if param_sh is not None:
            params = {n: jax.device_put(jnp.copy(jnp.asarray(a)),
                                        param_sh[n])
                      for n, a in params.items()}
        else:
            params = {n: jnp.copy(jnp.asarray(a))
                      for n, a in params.items()}
        self.params = params
        self.opt_state = optimizer.init_state_pytree(params)
        if param_sh is not None:
            self.opt_state = {
                n: jax.tree.map(
                    lambda a, _sh=param_sh[n], _p=params[n]: jax.device_put(
                        a, _sh)
                    if hasattr(a, "shape") and a.shape == _p.shape else a,
                    st)
                for n, st in self.opt_state.items()}
        self.step_count = jnp.zeros((), jnp.int32)

    def _dispatch_fn(self, *step_args):
        """The callable that executes this step — subclasses may return
        an AOT-compiled executable when the call signature matches it
        (TrainStep.compile)."""
        return self._jitted

    def _run_jitted(self, *step_args):
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        fn = self._dispatch_fn(*step_args)
        loss, self.params, self.opt_state, self.step_count = fn(
            self.params, self.opt_state, self.step_count, *step_args, lr)
        if self.optimizer._lr_scheduler is not None:
            self.optimizer._lr_scheduler.step()
        return loss

    # checkpointing ----------------------------------------------------------
    def state_dict(self):
        import numpy as np
        out = {"params": jax.tree.map(np.asarray, self.params),
               "opt_state": jax.tree.map(np.asarray, self.opt_state),
               "step": int(self.step_count)}
        # the dropout RNG chain rides along (when the subclass keeps
        # one) so a restored run's loss trajectory is bitwise identical
        # to the uninterrupted run — the property the peer-recovery
        # MTTR drill (bench --recovery-drill) asserts
        key = getattr(self, "_key", None)
        if key is not None:
            out["rng_key"] = np.asarray(key)
        if self.optimizer._lr_scheduler is not None:
            out["lr_scheduler"] = self.optimizer._lr_scheduler.state_dict()
        return out

    def set_state_dict(self, state):
        import numpy as np
        if self._param_sh:
            put = lambda n, a: jax.device_put(jnp.asarray(a),
                                              self._param_sh[n])
            # opt-state leaves shaped like their param share its sharding
            put_st = lambda n, st: jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), self._param_sh[n])
                if np.shape(a) == tuple(self.params[n].shape)
                else jnp.asarray(a), st)
        else:
            put = lambda n, a: jnp.asarray(a)
            put_st = lambda n, st: jax.tree.map(jnp.asarray, st)
        self.params = {n: put(n, a) for n, a in state["params"].items()}
        self.opt_state = {n: put_st(n, st)
                          for n, st in state["opt_state"].items()}
        self.step_count = jnp.asarray(state["step"], jnp.int32)
        if "rng_key" in state and hasattr(self, "_key"):
            self._key = jnp.asarray(np.asarray(state["rng_key"]),
                                    jnp.uint32)
        if "lr_scheduler" in state and \
                self.optimizer._lr_scheduler is not None:
            self.optimizer._lr_scheduler.set_state_dict(state["lr_scheduler"])


def _has_lm_loss(model) -> bool:
    """True when model.loss has the LM contract loss(input_ids, labels)
    — duck-typing on a bare attribute would misroute models whose loss
    takes a different signature (e.g. DiT's (x, t, y, noise))."""
    fn = getattr(model, "loss", None)
    if fn is None or not callable(fn):
        return False
    import inspect
    try:
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
    except (TypeError, ValueError):
        return False
    required = [p for p in params if p.default is p.empty]
    return len(required) == 2


def _loss_of(model, loss_fn, params, batch, rngs):
    """batch: dict with 'input_ids'/'labels' (LM) or (x, y) tuple routed to
    loss_fn(model_out, y).  A model exposing .loss(input_ids, labels)
    owns its objective (e.g. Llama's fused chunked lm-head+CE)."""
    if loss_fn is None:
        if _has_lm_loss(model):
            loss = functional_call(
                model, params, batch["input_ids"], batch["labels"],
                rngs=rngs, method="loss")
            return loss._data if hasattr(loss, "_data") else loss
        from paddle_tpu.nn.functional import cross_entropy
        out = functional_call(model, params, batch["input_ids"], rngs=rngs)
        logits = out._data if hasattr(out, "_data") else out
        v = logits.shape[-1]
        loss = cross_entropy(logits.reshape((-1, v)),
                             batch["labels"].reshape((-1,)))
        return loss._data if hasattr(loss, "_data") else loss
    x, y = batch
    out = functional_call(model, params, x, rngs=rngs)
    loss = loss_fn(out, y)
    return loss._data if hasattr(loss, "_data") else loss


class TrainStep(CompiledStepBase):
    """Compile model+optimizer into one donated, jitted update.

    step = TrainStep(model, opt)          # or loss_fn=, mesh=, param_specs=
    loss = step({"input_ids": ids, "labels": labels})
    step.sync_to_model()                  # write params back into the Layer
    """

    def __init__(self, model, optimizer, loss_fn: Optional[Callable] = None,
                 mesh=None, param_specs: Optional[Dict[str, Any]] = None,
                 batch_spec=None, compute_dtype=None, seed: int = 0,
                 remat: bool = False, remat_policy: Optional[str] = None,
                 analyze: Optional[str] = None, accum_steps: int = 1,
                 guard_nonfinite: Optional[bool] = None,
                 max_consecutive_skips: Optional[int] = None,
                 shardings=None, collective_overlap: Optional[bool] = None,
                 overlap_axis: str = "fsdp", sdc_sentinel=None,
                 sdc_check_interval: Optional[int] = None):
        # shardings=: an autoshard plan (analysis.autoshard.AutoShardPlan
        # — carries mesh shape, per-param specs and the batch spec in one
        # object) expands into the mesh/param_specs/batch_spec triple
        if shardings is not None:
            mesh, param_specs, batch_spec = _resolve_plan(
                shardings, mesh, param_specs, batch_spec)
        self.model = model
        self.loss_fn = loss_fn
        self.mesh = mesh
        # anomaly step-guard (robustness tentpole): a jitted all-finite
        # check on (loss, grad-norm); a NaN/Inf step SKIPS the optimizer
        # update — params, opt state and step_count come back bitwise
        # unchanged — instead of poisoning every weight.  Default ON
        # (PADDLE_TPU_STEP_GUARD=0 or guard_nonfinite=False disables);
        # after max_consecutive_skips straight skips the guard dumps the
        # flight recorder and raises NonFiniteStepError — a persistent
        # divergence must page someone, not spin forever.
        import os as _os
        if guard_nonfinite is None:
            guard_nonfinite = _os.environ.get(
                "PADDLE_TPU_STEP_GUARD", "1") != "0"
        self._guard_nonfinite = bool(guard_nonfinite)
        if max_consecutive_skips is None:
            max_consecutive_skips = int(_os.environ.get(
                "PADDLE_TPU_MAX_SKIP_STEPS", "25"))
        if max_consecutive_skips < 1:
            raise ValueError("max_consecutive_skips must be >= 1, got "
                             f"{max_consecutive_skips}")
        self._max_skips = max_consecutive_skips
        self._skip_streak = 0
        # microbatch gradient accumulation: the batch's leading axis is
        # split into accum_steps slices scanned sequentially with an fp32
        # grad carry — activation memory is per-MICROBATCH, so effective
        # batch grows without HBM blowup; equivalent to the full batch up
        # to accumulation order
        if int(accum_steps) < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self._accum_steps = int(accum_steps)
        # opt-in whole-step program analysis ("warn" prints findings on
        # the first step, "strict" raises on ERROR); default follows the
        # PADDLE_TPU_ANALYZE env var (paddle_tpu.analysis.analysis_mode)
        self._analyze_mode = analyze
        self._analyzed = False
        # (no copy here: _init_step_state copies every leaf before the
        # donated jit, which is what protects the Layer's own Parameters)
        params = params_of(model, dtype=compute_dtype)
        self._mask = trainable_mask(model)
        self._key = jax.random.PRNGKey(seed)
        self._remat = remat
        # named XLA remat policies (SURVEY hard-part: trade FLOPs for HBM);
        # 'dots' saves matmul outputs and recomputes elementwise — near
        # no-remat throughput at a fraction of the activation memory
        self._remat_policy_name = remat_policy
        if remat_policy is None:
            self._remat_policy = None
        else:
            from jax.ad_checkpoint import checkpoint_policies as cp
            policies = {
                "dots": cp.checkpoint_dots,
                "dots_no_batch": cp.checkpoint_dots_with_no_batch_dims,
                "nothing": cp.nothing_saveable,
                "everything": cp.everything_saveable,
            }
            if remat_policy not in policies:
                raise ValueError(
                    f"unknown remat_policy {remat_policy!r}; "
                    f"choose from {sorted(policies)}")
            self._remat_policy = policies[remat_policy]

        if mesh is not None and param_specs is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def sanitize(spec):
                # model partition rules name every axis they know about
                # (dp/fsdp/tp/ep); drop the ones absent from this mesh so a
                # ('dp','ep') mesh accepts Llama-style tp rules unchanged
                axes = set(mesh.axis_names)

                def keep(e):
                    if e is None or (not isinstance(e, tuple) and e in axes):
                        return e
                    if isinstance(e, tuple):
                        kept = tuple(a for a in e if a in axes)
                        return kept if kept else None
                    return None
                return P(*(keep(e) for e in spec))

            to_sh = lambda spec: NamedSharding(mesh, sanitize(spec))
            param_sh = {n: to_sh(param_specs.get(n, P())) for n in params}
            self._batch_sh = to_sh(batch_spec) if batch_spec is not None \
                else None
        else:
            param_sh = self._batch_sh = None

        # compute/collective overlap (ISSUE 15): express the per-layer
        # FSDP weight all-gathers as an explicit, layer-ordered prefetch
        # chain (issue order decoupled from consumers) so XLA's async
        # scheduler hides them under the previous layer's compute.
        # Knob-gated (PADDLE_TPU_COLLECTIVE_OVERLAP / collective_overlap=)
        # and default off = exact previous jaxpr; only arms when a mesh
        # axis actually shards weights on ``overlap_axis``.
        from paddle_tpu.distributed.sharding import (gathered_spec,
                                                     overlap_enabled,
                                                     prefetch_groups,
                                                     spec_mentions_axis)
        if collective_overlap is None:
            collective_overlap = overlap_enabled()
        self._overlap_axis = overlap_axis
        self._collective_overlap = False
        self._overlap_groups = None
        self._gathered_sh = None
        if collective_overlap and mesh is not None and \
                param_sh is not None and overlap_axis in mesh.axis_names:
            from jax.sharding import NamedSharding
            gathered = {
                n: NamedSharding(mesh, gathered_spec(sh.spec, overlap_axis))
                for n, sh in param_sh.items()
                if spec_mentions_axis(sh.spec, overlap_axis)}
            if gathered:
                self._gathered_sh = gathered
                self._overlap_groups = prefetch_groups(sorted(gathered))
                self._collective_overlap = True

        # optional SDC sentinel hook (robustness.recovery.SDCSentinel):
        # publish/verify the params digest across DP peers every
        # ``sdc_check_interval`` applied steps — the TrainStep-driven
        # form of the PR-14 loop-driven sentinel
        self._sdc_sentinel = sdc_sentinel
        if sdc_check_interval is None:
            sdc_check_interval = getattr(sdc_sentinel, "interval", 1) \
                if sdc_sentinel is not None else 0
        if sdc_sentinel is not None and int(sdc_check_interval) < 1:
            raise ValueError("sdc_check_interval must be >= 1, got "
                             f"{sdc_check_interval}")
        self._sdc_interval = int(sdc_check_interval or 0)
        self.last_sdc_verdict = None

        self._init_step_state(optimizer, params, param_sh)
        self._jitted = jax.jit(self._step_impl, donate_argnums=(0, 1, 2))
        # AOT path (device-profiler tentpole): compile(batch) stores the
        # explicit lower().compile() executable here; calls whose batch
        # signature matches dispatch through it (no retrace hazard, and
        # the executable's cost/memory analysis feeds the MFU gauge)
        self._compiled = None
        self._compiled_sig = None
        self._exe_flops = None
        self._peak_flops = None
        self._cache_probed = False
        # per-step HBM watermark sampling (leak detection rides on it);
        # PADDLE_TPU_DEVICE_WATERMARK=0 disables, _WATERMARK_INTERVAL
        # thins it (the sweep is O(live arrays))
        self._memmon = None
        self._watermark_every = max(1, int(_os.environ.get(
            "PADDLE_TPU_WATERMARK_INTERVAL", "1")))
        if _os.environ.get("PADDLE_TPU_DEVICE_WATERMARK", "1") != "0":
            from paddle_tpu.observability.device_profiler import \
                device_memory_monitor
            self._memmon = device_memory_monitor()

        # always-on telemetry (observability tentpole): metric writes are
        # dict lookups + float adds; the loss / grad-norm gauges hold the
        # DEVICE scalar and only float() when an exporter scrapes, so the
        # hot path never blocks on the device
        self._metrics = _train_metrics()
        from paddle_tpu.observability import flight_recorder
        from paddle_tpu.observability.tracing import tracer
        self._recorder = flight_recorder()
        self._tracer = tracer()
        from paddle_tpu.analysis.recompile import SignatureMonitor
        self._signature_monitor = SignatureMonitor(
            name=f"TrainStep({type(model).__name__})")
        self._host_steps = 0
        self._step_ema: Optional[float] = None

    def _overlap_prefetch(self, params):
        """Issue every ZeRO-3 weight all-gather as an explicit,
        layer-ordered chain: ``with_sharding_constraint`` to the
        axis-free layout forces GSPMD to materialize the gather here —
        decoupled from the layer that consumes it — and the
        ``optimization_barrier`` chain pins issue order layer i → i+1,
        so the scheduler streams the gathers as a prefetch queue it can
        hide under earlier layers' compute instead of paying each one
        just-in-time at its consumer."""
        from paddle_tpu.distributed.sharding import overlap_path_counter
        overlap_path_counter().labels(path="fsdp_prefetch").inc()
        out = dict(params)
        token = None
        for group in self._overlap_groups:
            nxt = None
            for n in group:
                p = jax.lax.with_sharding_constraint(
                    params[n], self._gathered_sh[n])
                if token is not None:
                    p = _ordered_after(p, token)
                if nxt is None:
                    nxt = p
                out[n] = p
            token = nxt if nxt is not None else token
        return out

    def _step_impl(self, params, opt_state, step_count, batch, key, lr):
        model, opt = self.model, self.optimizer

        def loss_of_trainable(train_params, frozen_params, mb, k):
            full = dict(frozen_params)
            full.update(train_params)
            if self._collective_overlap:
                full = self._overlap_prefetch(full)
            f = lambda p: _loss_of(model, self.loss_fn, p, mb,
                                   {"dropout": k})
            if self._remat:
                f = jax.checkpoint(f, policy=self._remat_policy)
            return f(full)

        train_p = {n: v for n, v in params.items() if self._mask.get(n)}
        frozen_p = {n: v for n, v in params.items() if not self._mask.get(n)}
        n_acc = self._accum_steps
        if n_acc == 1:
            loss, grads = jax.value_and_grad(loss_of_trainable)(
                train_p, frozen_p, batch, key)
        else:
            # scan over microbatches: loss/grads are the mean over slices
            # (each slice weights equally, matching the full-batch mean
            # for equal-size microbatches); the fp32 carry is donated
            # buffer-reuse inside the scan, so peak memory holds ONE
            # microbatch's activations + one fp32 grad copy
            micro = jax.tree.map(
                lambda a: a.reshape((n_acc, a.shape[0] // n_acc)
                                    + a.shape[1:]), batch)
            keys = jax.random.split(key, n_acc)
            inv = 1.0 / n_acc

            def one_micro(carry, xs):
                loss_acc, g_acc = carry
                mb, k = xs
                l, g = jax.value_and_grad(loss_of_trainable)(
                    train_p, frozen_p, mb, k)
                g_acc = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32) * inv, g_acc, g)
                return (loss_acc + l.astype(jnp.float32) * inv, g_acc), None

            g0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                              train_p)
            (loss, grads), _ = jax.lax.scan(
                one_micro, (jnp.zeros((), jnp.float32), g0), (micro, keys))
        # global grad norm for the telemetry gauge: one vdot per leaf —
        # noise next to the backward pass it rides on
        gnorm = jnp.sqrt(sum(
            (jnp.vdot(g, g).real for g in jax.tree.leaves(grads)),
            start=jnp.zeros((), jnp.float32)))
        step_count = step_count + 1
        new_train, new_state = opt.apply_gradients(
            train_p, grads,
            {n: opt_state[n] for n in train_p}, step_count, lr=lr)
        new_params = dict(frozen_p)
        new_params.update(new_train)
        new_opt_state = dict(opt_state)
        new_opt_state.update(new_state)
        # non-finite step-guard: skip_code 0 = applied, 1 = non-finite
        # loss, 2 = finite loss but non-finite grad norm (a single
        # NaN/Inf anywhere in the grads poisons the norm, so one scalar
        # check covers every leaf).  On skip, a jnp.where per leaf keeps
        # the OLD params/opt state/step_count — the anomalous update is
        # fully discarded on device; no host round-trip decides anything.
        if self._guard_nonfinite:
            skip_code = jnp.where(
                jnp.isfinite(loss),
                jnp.where(jnp.isfinite(gnorm), 0, 2), 1).astype(jnp.int32)
            keep = skip_code == 0

            def sel(new, old):
                return jax.tree.map(
                    lambda a, b: jnp.where(keep, a, b), new, old)

            new_params = sel(new_params, params)
            new_opt_state = sel(new_opt_state, opt_state)
            step_count = jnp.where(keep, step_count, step_count - 1)
        else:
            skip_code = jnp.zeros((), jnp.int32)
        return (loss, gnorm, skip_code), new_params, new_opt_state, \
            step_count

    def _place_batch(self, batch):
        """Device placement shared by the call path and compile():
        sharded device_put under a mesh, plain asarray otherwise
        (device-prefetched batches are already resident — no-op)."""
        if self._batch_sh is not None:
            return jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), self._batch_sh),
                batch)
        return jax.tree.map(jnp.asarray, batch)

    def _cache_extra(self) -> str:
        """Compile-cache key discriminators the call-argument avals
        can't see: closed-over step config plus the model config that
        bakes constants (rope tables, eps) into the trace."""
        from paddle_tpu import compile_cache
        lf = getattr(self.loss_fn, "__name__", repr(self.loss_fn)) \
            if self.loss_fn is not None else ""
        return (f"model={compile_cache.model_config_tag(self.model)}"
                f"|opt={type(self.optimizer).__name__}"
                f"|loss={lf}|accum={self._accum_steps}"
                f"|remat={int(self._remat)}:{self._remat_policy_name}"
                f"|guard={int(self._guard_nonfinite)}"
                f"|ovl={int(self._collective_overlap)}")

    def compile(self, batch):
        """AOT-compile the step for this batch signature with full
        compile observability: ``train.compile`` span (with
        ``compile.lower`` / ``compile.xla`` children), the per-target
        compile counter, and the executable's measured FLOPs / HBM
        bytes / peak memory exposed as ``paddle_tpu_xla_*`` gauges.
        With ``PADDLE_TPU_COMPILE_CACHE=1`` the persistent executable
        cache is consulted first: a hit deserialize-and-loads under a
        ``compile.cache_hit`` span instead of lower→compile, and a
        live compile's executable is stored for the next boot.
        Subsequent calls whose batch matches dispatch through the
        compiled executable (no retrace), and the step starts setting
        the ``paddle_tpu_train_mfu`` gauge.  Returns the
        :class:`~paddle_tpu.observability.device_profiler.CompileInfo`.
        """
        from paddle_tpu import compile_cache
        from paddle_tpu.observability.device_profiler import signature_of
        batch = self._place_batch(batch)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        target = f"TrainStep({type(self.model).__name__})"
        with self._tracer.span("train.compile", target=target):
            compiled, info, _hit = compile_cache.aot_compile_cached(
                self._jitted, self.params, self.opt_state,
                self.step_count, batch, self._key, lr, target=target,
                mesh=self.mesh, shardings=self._param_sh,
                extra=self._cache_extra())
        self._compiled = compiled
        self._compiled_sig = signature_of(batch)
        self._exe_flops = info.stats.flops or None
        return info

    def _probe_compile_cache(self, batch):
        """Transparent cold-start adoption: the FIRST plain call checks
        the persistent cache for this exact step signature — a restarted
        worker that never calls compile() still boots without an XLA
        compile when the cache is warm.  Misses leave the jit path
        untouched; failures never escape (a stale cache must not break
        a boot)."""
        self._cache_probed = True
        try:
            from paddle_tpu import compile_cache
            if not compile_cache.enabled():
                return
            from paddle_tpu.observability.device_profiler import \
                signature_of
            lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
            target = f"TrainStep({type(self.model).__name__})"
            compiled, info, hit = compile_cache.aot_compile_cached(
                self._jitted, self.params, self.opt_state,
                self.step_count, batch, self._key, lr, target=target,
                mesh=self.mesh, shardings=self._param_sh,
                extra=self._cache_extra(), cache_only=True)
            if hit:
                self._compiled = compiled
                self._compiled_sig = signature_of(batch)
                self._exe_flops = info.stats.flops or None
        except Exception:
            pass

    def _dispatch_fn(self, *step_args):
        if self._compiled is not None:
            from paddle_tpu.observability.device_profiler import \
                signature_of
            if signature_of(step_args[0]) == self._compiled_sig:
                return self._compiled
        return self._jitted

    def __call__(self, batch):
        # step span: children cover h2d placement, the compiled dispatch
        # (with the accum scan as a nested level), and the step-guard's
        # device sync — a slow step names its slow phase in the trace
        with self._tracer.span("train.step", step=self._host_steps,
                               accum=self._accum_steps):
            return self._call_traced(batch)

    def _call_traced(self, batch):
        # chaos: poison this batch's float leaves with NaN — the
        # injectable twin of a corrupt record / bad-loss microbatch,
        # which the step-guard must absorb (int-only LM batches have no
        # poisonable leaf; use a float-input model to drill this path)
        from paddle_tpu.robustness import fault_fires
        if fault_fires("train.nonfinite_batch", step=self._host_steps):
            batch = jax.tree.map(
                lambda a: a * jnp.nan
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                else a, batch)
        with self._tracer.span("train.h2d"):
            batch = self._place_batch(batch)
        if self._compiled is None and not self._cache_probed:
            self._probe_compile_cache(batch)
        if self._accum_steps > 1:
            for leaf in jax.tree.leaves(batch):
                if getattr(leaf, "ndim", 0) and \
                        leaf.shape[0] % self._accum_steps:
                    raise ValueError(
                        f"batch leading dim {leaf.shape[0]} not divisible "
                        f"by accum_steps={self._accum_steps}")
        if not self._analyzed:
            self._maybe_analyze(batch)
        # recompile telemetry: a novel signature after the first call IS
        # a retrace (jax.jit keys its executable cache the same way)
        novel = self._signature_monitor.record((batch,))
        if novel and self._signature_monitor.calls > 1:
            self._metrics["recompiles"].inc()
            self._recorder.record(
                "train.recompile",
                target=self._signature_monitor.name,
                distinct_signatures=len(self._signature_monitor.records))
        self._key, sub = jax.random.split(self._key)
        t0 = time.perf_counter()
        # chaos: per-host step delay INSIDE the timed region — the
        # injectable straggler whose inflated step EMA the fleet
        # straggler rule must catch (delay via
        # PADDLE_TPU_STRAGGLER_DELAY_S, default 50ms)
        if fault_fires("train.straggler_delay", step=self._host_steps):
            import os as _os
            time.sleep(float(_os.environ.get(
                "PADDLE_TPU_STRAGGLER_DELAY_S", "0.05")))
        with self._recorder.instrumented("train.step",
                                         step=self._host_steps):
            with self._tracer.span("train.dispatch",
                                   microbatches=self._accum_steps):
                if self._accum_steps > 1:
                    # the scan runs on device as ONE program; this child
                    # span marks the accumulated region so the trace
                    # shows dispatch time is microbatch work, not gap
                    with self._tracer.span("train.accum_microbatches",
                                           n=self._accum_steps):
                        loss, gnorm, skip_code = self._run_jitted(batch,
                                                                  sub)
                else:
                    loss, gnorm, skip_code = self._run_jitted(batch, sub)
        dt = time.perf_counter() - t0
        self._host_steps += 1
        m = self._metrics
        m["step"].observe(dt)
        m["steps"].inc()
        m["accum"].observe(self._accum_steps)
        m["loss"].set(loss)     # device scalar, resolved at scrape
        m["gnorm"].set(gnorm)
        self._step_ema = dt if self._step_ema is None \
            else 0.8 * self._step_ema + 0.2 * dt
        m["ema"].set(self._step_ema)
        if self._guard_nonfinite:
            # the int() sync IS the guard's cost; the span makes it
            # visible instead of smearing into "step overhead"
            with self._tracer.span("train.guard"):
                code = int(skip_code)
                # goodput split BEFORE _account_skip may raise: a
                # discarded update is lost time, not productive time
                m["productive" if code == 0 else "skipped_s"].inc(dt)
                self._account_skip(code)
        else:
            m["productive"].inc(dt)
        tokens = self._batch_tokens(batch)
        if tokens:
            m["tokens"].inc(tokens)
            if dt > 0:
                m["tps"].set(tokens / dt)
        # measured MFU: the AOT executable's XLA-counted FLOPs over this
        # step's wall time — the drift gauge the mfu_drift SLO rule
        # watches (only armed once compile(batch) introspected the step)
        if self._exe_flops and dt > 0:
            if self._peak_flops is None:
                from paddle_tpu.observability.device_profiler import \
                    detect_roofline
                self._peak_flops = detect_roofline()[0]
            m["mfu"].set(self._exe_flops / dt / self._peak_flops)
        if self._memmon is not None and \
                (self._host_steps % self._watermark_every) == 0:
            self._memmon.sample(step=self._host_steps)
        # SDC sentinel cadence: publish this rank's params digest and
        # judge it against the DP peers' (bounded wait = the sentinel's
        # timeout).  Mismatch handling (metrics, flight-recorder dump,
        # blame, quarantine) lives in the sentinel itself.
        if self._sdc_sentinel is not None and \
                self._host_steps % self._sdc_interval == 0:
            self._sdc_sentinel.publish(self._host_steps, self.params)
            self.last_sdc_verdict = self._sdc_sentinel.verify(
                self._host_steps)
        return loss

    def _account_skip(self, code: int):
        """Host side of the step-guard: metric + flight-recorder entry
        per skipped step, escape hatch after K consecutive skips.  The
        ``int(skip_code)`` in __call__ is the guard's one cost — it
        synchronizes on the step (the price of knowing in time)."""
        if code == 0:
            self._skip_streak = 0
            return
        reason = "nonfinite_loss" if code == 1 else "nonfinite_grad"
        self._skip_streak += 1
        self._metrics["skipped"].labels(reason=reason).inc()
        self._recorder.record("train.step_skipped", reason=reason,
                              step=self._host_steps - 1,
                              streak=self._skip_streak)
        if self._skip_streak >= self._max_skips:
            from paddle_tpu.robustness import NonFiniteStepError
            self._recorder.dump(
                reason=f"step-guard: {self._skip_streak} consecutive "
                       f"non-finite steps ({reason})")
            raise NonFiniteStepError(
                f"{self._skip_streak} consecutive optimizer updates "
                f"skipped (last reason: {reason}) — persistent "
                "divergence, not a transient bad microbatch; params are "
                "unchanged since the last finite step")

    @staticmethod
    def _batch_tokens(batch) -> int:
        """Token count for throughput metrics: LM batches count
        input_ids elements, (x, y) batches count examples."""
        if isinstance(batch, dict) and "input_ids" in batch:
            ids = batch["input_ids"]
            return int(ids.size) if hasattr(ids, "size") else 0
        leaves = jax.tree.leaves(batch)
        if leaves and getattr(leaves[0], "ndim", 0):
            return int(leaves[0].shape[0])
        return 0

    def _maybe_analyze(self, batch):
        self._analyzed = True
        from paddle_tpu.analysis import analysis_mode
        mode = self._analyze_mode if self._analyze_mode is not None \
            else analysis_mode()
        if not mode:
            return
        import sys
        report = self.analyze(batch, strict=(mode == "strict"))
        if len(report):
            print(report.format(), file=sys.stderr)

    def analyze(self, batch, strict: bool = False, passes=None,
                options=None):
        """Run the ``paddle_tpu.analysis`` pass pipeline over the whole
        compiled step (fwd+bwd+update) with this step's parameter
        shardings.  Abstract — no step executes."""
        import paddle_tpu.analysis as _analysis
        return _analysis.check(self, batch, strict=strict, passes=passes,
                               options=options)

    def sync_to_model(self):
        state = self.model.state_dict(keep_vars=True)
        for n, arr in self.params.items():
            state[n]._set_data(arr.astype(state[n]._data.dtype))
