"""dy2static: AST capture of data-dependent Python control flow.

Reference parity: python/paddle/jit/dy2static (program_translator.py:305
+ the transformer pipeline: ifelse_transformer, loop_transformer, ...) —
15k LoC rewriting dygraph Python into static-graph ops.  TPU-native: the
target isn't a ProgramDesc but jaxpr — ``if``/``while``/``for-range``
statements become calls to runtime helpers that pick plain Python when
the condition is concrete (eager) and ``lax.cond`` / ``lax.while_loop``
when it is traced (inside jit), so ONE source serves both modes.

Supported: If / While / for-over-range including tuple/aug assignments,
``break`` / ``continue`` inside converted loops (rewritten to guarded
flags — reference break_continue_transformer.py), early ``return``
anywhere (rewritten to a flag + return-value slot — reference
return_transformer.py), and container state inside compound statements
(reference list_transformer.py / dict assignment handling):
``lst.append(x)`` and ``d[k] = v`` / ``d[k] += v`` are rewritten to
functional re-assignments (``lst = lst + [x]``, ``d = {**d, k: v}``) so
the container rides the carry/branch tuples like any other local.  A
loop with a concrete trip count that grows a list therefore UNROLLS
under trace (each iteration changes the carry's pytree structure, which
``lax.while_loop`` cannot carry — same restriction the reference works
around with LoDTensorArray); a loop whose continuation is TRACED may
not grow containers and says so.  Caveat shared with the reference's
transformers: the functional rewrite breaks aliasing — mutations are
visible through the rewritten NAME, not through other references to the
same container.  Genuinely dynamic structure (data-dependent shapes,
`return` of differently-typed values per branch, iteration over traced
non-range iterables) still raises a clear error at trace time, like the
reference's transformer diagnostics.  Nested function defs (used within
their scope) and ``try/except`` convert fine — the try executes at
trace time and its control-flow statements get the standard rewrites.
A function DEF whose name must escape a converted branch is the
documented exception (function values cannot ride a lax.cond carry):
the name fails at its use site; define the variants before the if and
branch on data instead.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax

__all__ = ["convert_to_static", "cond_call", "while_call",
           "UNDEF", "undef_lookup"]


# ---------------------------------------------------------------- runtime

def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _concrete_bool(x):
    if hasattr(x, "_data"):
        x = x._data
    return bool(x)


class _Undef:
    """Sentinel for a name assigned in only one branch and unbound in
    the other (reference dy2static's UndefinedVar)."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()


def undef_lookup(thunk):
    """Read a possibly-unbound outer name: its value, or UNDEF."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def cond_call(pred, true_fn, false_fn, operands, needed):
    """if-statement runtime: python branch when concrete, lax.cond when
    traced.  Branch fns take the tuple of current values of every name
    the branches assign (UNDEF where unbound) and return the updated
    tuple; ``needed[i]`` marks operands whose INCOMING value matters
    (names not re-assigned by both branches)."""
    raw = pred._data if hasattr(pred, "_data") else pred
    if not _is_traced(raw):
        return true_fn(operands) if _concrete_bool(raw) \
            else false_fn(operands)
    fixed = []
    for v, need in zip(operands, needed):
        if v is UNDEF:
            if need:
                raise TypeError(
                    "dy2static: a variable assigned in only one branch of "
                    "a TRACED `if` has no prior definition; initialise it "
                    "before the if so both branches agree on its type")
            # both branches overwrite it: a placeholder keeps lax.cond's
            # operand pytree valid, the incoming value is never used
            fixed.append(jax.numpy.zeros(()))
        else:
            fixed.append(v)
    if any(v is None for v in fixed):
        # None marks the not-yet-set early-return value (__jst_rv): its
        # type comes from whichever branch assigns it — trace the branches
        # abstractly with scalar probes, then seed a typed zeros
        # placeholder (sound: the value is only ever READ under the
        # return flag, which is False until a real assignment happened)
        import jax.numpy as jnp
        probe = tuple(jnp.zeros(()) if v is None else v for v in fixed)
        branch_avals = []
        for branch in (true_fn, false_fn):
            try:
                branch_avals.append(jax.eval_shape(branch, probe))
            except Exception:
                pass
        new_fixed = []
        for i, v in enumerate(fixed):
            if v is not None:
                new_fixed.append(v)
                continue
            # prefer the branch that actually ASSIGNED the slot (its aval
            # differs from the scalar probe); scalar zero if neither did
            aval = None
            probe_aval = jax.eval_shape(lambda: probe[i])
            for avs in branch_avals:
                a = avs[i]
                # the assigning branch's output differs from the probe
                # in SHAPE OR DTYPE (an int return must not be seeded
                # with a float placeholder)
                if (a.shape, a.dtype) != (probe_aval.shape,
                                          probe_aval.dtype) \
                        or len(branch_avals) == 1:
                    aval = a
                    break
            new_fixed.append(jnp.zeros(aval.shape, aval.dtype)
                             if aval is not None else jnp.zeros(()))
        fixed = new_fixed
    try:
        return jax.lax.cond(raw, true_fn, false_fn, tuple(fixed))
    except TypeError as e:
        raise TypeError(
            "dy2static: the branches of a TRACED `if` must bind the same "
            "variables with matching shapes/dtypes (early returns under a "
            "traced condition must be type-stable across paths; branches "
            "must add the same dict keys / append the same number of list "
            "elements)") from e


def bool_not(x):
    """Traced-safe `not` (the early-exit flags may be traced)."""
    raw = x._data if hasattr(x, "_data") else x
    if _is_traced(raw):
        import jax.numpy as jnp
        return jnp.logical_not(raw)
    return not raw


def bool_and(a, b):
    ar = a._data if hasattr(a, "_data") else a
    br = b._data if hasattr(b, "_data") else b
    if _is_traced(ar) or _is_traced(br):
        import jax.numpy as jnp
        return jnp.logical_and(ar, br)
    return ar and br


def bool_or(a, b):
    ar = a._data if hasattr(a, "_data") else a
    br = b._data if hasattr(b, "_data") else b
    if _is_traced(ar) or _is_traced(br):
        import jax.numpy as jnp
        return jnp.logical_or(ar, br)
    return ar or br


def list_append(x, y):
    """Functional ``x.append(y)`` — the rewrite target for appends inside
    converted compound statements.  Lists/tuples get a NEW container (so
    the name can ride a carry/branch tuple); anything else with a real
    .append (e.g. a TensorArray) keeps its own mutating semantics."""
    if isinstance(x, list):
        return x + [y]
    if isinstance(x, tuple):
        return x + (y,)
    if x is UNDEF:
        raise TypeError(
            "dy2static: .append() on a variable with no prior value in "
            "this path; initialise the list before the loop/branch")
    x.append(y)
    return x


def container_setitem(x, k, v):
    """Functional ``x[k] = v`` — dicts/lists become new containers;
    tensors/arrays go through their own setitem (Tensor mutates in place,
    raw jax arrays use the functional .at update)."""
    if isinstance(x, dict):
        out = dict(x)
        out[k] = v
        return out
    if isinstance(x, list):
        out = list(x)
        out[k] = v
        return out
    if x is UNDEF:
        raise TypeError(
            "dy2static: item assignment on a variable with no prior value "
            "in this path; initialise the container before the loop/branch")
    if hasattr(x, "__setitem__"):
        x[k] = v
        return x
    return x.at[k].set(v)  # immutable jax array


def range_cont(i, stop, step):
    """Continuation test for a rewritten for-range: sign-aware."""
    import jax.numpy as jnp
    raw = step._data if hasattr(step, "_data") else step
    if not _is_traced(raw):
        return i < stop if _concrete_bool(raw > 0) else i > stop
    return jnp.where(raw > 0, i < stop, i > stop)


def while_call(cond_fn, body_fn, carry, seedable=None):
    """while-statement runtime: carry is the tuple of loop variables.

    UNDEF entries are body-local temps with no pre-loop value; entries
    marked ``seedable`` (statically proven written-before-read in the
    body — e.g. a nested loop's induction/flag temps) get a typed zeros
    placeholder inferred from one abstract body evaluation; the rest
    raise loudly.  ``None`` entries are not-yet-set early-return values,
    promoted the same way."""
    first = cond_fn(carry)
    raw = first._data if hasattr(first, "_data") else first
    if not _is_traced(raw):
        # python path while the test stays concrete; a traced `if` inside
        # the body (e.g. an early return on traced data) can inject
        # tracers into the carry mid-loop — hand the REMAINING iterations
        # to lax.while_loop then instead of crashing on bool(tracer).
        # Exception: a body that GROWS the carry's pytree structure
        # (functionalized list.append, new dict keys) must keep
        # unrolling — lax.while_loop cannot carry a changing structure
        while True:
            c = cond_fn(carry)
            craw = c._data if hasattr(c, "_data") else c
            if _is_traced(craw):
                break
            if not bool(craw):
                return carry
            new = body_fn(carry)
            grew = (jax.tree.structure(new, is_leaf=lambda v: v is UNDEF)
                    != jax.tree.structure(carry,
                                          is_leaf=lambda v: v is UNDEF))
            carry = new
            if not grew and any(
                    _is_traced(v._data if hasattr(v, "_data") else v)
                    for v in jax.tree.leaves(carry)):
                break

    if seedable is None:
        seedable = (False,) * len(carry)
    if any(v is UNDEF and not s for v, s in zip(carry, seedable)):
        raise TypeError(
            "dy2static: a TRACED `while` body introduces a variable with "
            "no pre-loop value; initialise it before the loop so the "
            "carry has a stable type")

    if any(v is UNDEF or v is None for v in carry):
        # infer placeholder types from one abstract body evaluation
        # (cond_call promotes inner Nones); sound for seedable slots —
        # their pre-loop value is never read
        import jax.numpy as jnp
        probe = tuple(jnp.zeros(()) if v is UNDEF else v for v in carry)
        try:
            avals = jax.eval_shape(body_fn, probe)
            carry = tuple(
                jnp.zeros(a.shape, a.dtype)
                if (v is None or v is UNDEF) else v
                for v, a in zip(carry, avals))
        except Exception:
            carry = tuple(jnp.zeros(()) if (v is None or v is UNDEF)
                          else v for v in carry)

    def cond_raw(c):
        out = cond_fn(c)
        return out._data if hasattr(out, "_data") else out

    try:
        return jax.lax.while_loop(cond_raw, body_fn, carry)
    except TypeError as e:
        if "structure" in str(e) or "pytree" in str(e):
            raise TypeError(
                "dy2static: the body of a loop with a TRACED continuation "
                "changes the carried pytree structure (list.append / new "
                "dict keys per iteration). lax.while_loop cannot grow its "
                "carry; make the trip count concrete (the loop then "
                "unrolls) or preallocate a fixed-size buffer "
                "(jnp.zeros + index update, or TensorArray under lax.scan)"
            ) from e
        raise


# ------------------------------------------------------------ the rewrite

class _Unsupported(NotImplementedError):
    pass


def _assigned_names(nodes):
    """Simple-Name store targets in a statement list (recursively)."""
    out = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Store) and node.id not in out:
                out.append(node.id)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) and \
                    node.target.id not in out:
                out.append(node.target.id)
            self.generic_visit(node)

        # nested defs own their scope
        def visit_FunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        # py3 comprehension targets are scoped to the comprehension — they
        # are NOT branch-local assignments (walrus escapes are not handled)
        def _skip(self, node):
            pass

        visit_ListComp = visit_SetComp = _skip
        visit_GeneratorExp = visit_DictComp = _skip

    for n in nodes:
        V().visit(n)
    return out


def _read_before_store(nodes):
    """Names Loaded before their first Store, in (approximate) execution
    order — an UNDEF placeholder for such a name could actually be read,
    so it must be treated as `needed` (loud error instead of a silent 0)."""
    stored = set()
    reads = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load) and node.id not in stored:
                reads.add(node.id)
            elif isinstance(node.ctx, ast.Store):
                stored.add(node.id)

        def visit_Assign(self, node):  # value is evaluated before targets
            self.visit(node.value)
            for t in node.targets:
                self.visit(t)

        def visit_AugAssign(self, node):  # target is read, then written
            self.visit(node.value)
            if isinstance(node.target, ast.Name):
                if node.target.id not in stored:
                    reads.add(node.target.id)
                stored.add(node.target.id)
            else:
                self.visit(node.target)

        def visit_Call(self, node):
            # __jst_undef_lookup(lambda: name) is the transformer's OWN
            # safe read (returns UNDEF instead of raising) — not a user
            # read; skip it so already-rewritten inner ifs don't mark
            # every assigned name as read-before-store
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "__jst_undef_lookup":
                return
            self.generic_visit(node)

        def _visit_comp(self, node):
            # a comprehension's generators run before its elt, and its
            # targets are scoped to it — visit in execution order with the
            # targets counting as stores (conservatively left in `stored`)
            for gen in node.generators:
                self.visit(gen.iter)
                self.visit(gen.target)
                for cond in gen.ifs:
                    self.visit(cond)
            if hasattr(node, "elt"):
                self.visit(node.elt)
            else:  # DictComp
                self.visit(node.key)
                self.visit(node.value)

        visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp
        visit_DictComp = _visit_comp

        def _visit_closure(self, node):
            # free variables of a nested def/lambda may be read when it is
            # called — count its Loads (minus its own args) as reads.
            # Functions the transformer itself generated (rewritten inner
            # ifs/whiles) are exempt: their reads go through the carry
            # tuple / undef_lookup machinery, not bare unbound names.
            if getattr(node, "name", "").startswith("__jst_"):
                return
            args = {a.arg for a in node.args.args + node.args.posonlyargs
                    + node.args.kwonlyargs}
            for a in (node.args.vararg, node.args.kwarg):
                if a is not None:
                    args.add(a.arg)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id not in args and sub.id not in stored:
                    reads.add(sub.id)

        visit_FunctionDef = visit_AsyncFunctionDef = _visit_closure
        visit_Lambda = _visit_closure

    v = V()
    for n in nodes:
        v.visit(n)
    return reads


def _read_names(nodes):
    out = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                out.add(node.id)

    for n in nodes:
        V().visit(n)
    return out


def _check_no_flow_escape(nodes, what):
    """Break/continue/return that survived the early-exit rewrites (e.g.
    inside non-range for loops the converter leaves as python) still can't
    be functionalized — keep the loud diagnostic."""
    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            raise _Unsupported(
                f"dy2static: `return` inside a converted {what} is not "
                "supported; assign to a variable and return after it")

        def visit_Break(self, node):
            raise _Unsupported(
                f"dy2static: `break` inside a converted {what} is not "
                "supported; fold the exit condition into the loop test")

        def visit_Continue(self, node):
            raise _Unsupported(
                f"dy2static: `continue` inside a converted {what} is not "
                "supported")

        def visit_FunctionDef(self, node):
            pass

    for n in nodes:
        V().visit(n)


# -- early-exit rewrites (reference: jit/dy2static's
#    break_continue_transformer.py + return_transformer.py) ------------------

def _name(n, ctx=ast.Load):
    return ast.Name(id=n, ctx=ctx())

def _assign(target, value):
    return ast.Assign(targets=[_name(target, ast.Store)], value=value)

def _call(fn, *args):
    return ast.Call(func=_name(fn), args=list(args), keywords=[])

def _not(expr):
    # traced-safe: the flags these expressions read may be jax tracers
    return _call("__jst_not", expr)

def _and(a, b):
    return _call("__jst_and", a, b)

def _or(a, b):
    return _call("__jst_or", a, b)


def _contains_here(nodes, types, *, through_loops=True):
    """Does any statement contain a node of `types`, NOT descending into
    nested function defs (and optionally not into nested loops — break /
    continue bind to the nearest loop)?"""
    found = []

    class V(ast.NodeVisitor):
        def generic_visit(self, node):
            if isinstance(node, types):
                found.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if not through_loops and isinstance(node, (ast.While, ast.For)):
                return
            super().generic_visit(node)

    for n in nodes:
        V().visit(n)
    return len(found) > 0


class _BreakContinueRewriter(ast.NodeTransformer):
    """Replace this loop's break/continue with flag assignments (does not
    descend into nested loops or defs — they own their own statements)."""

    def __init__(self, brk, cont):
        self.brk = brk
        self.cont = cont

    def visit_Break(self, node):
        return _assign(self.brk, ast.Constant(True))

    def visit_Continue(self, node):
        return _assign(self.cont, ast.Constant(True))

    def visit_While(self, node):
        return node  # nested loop: its breaks are its own

    def visit_For(self, node):
        return node

    def visit_FunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node


def _guard_tail(stmts, flag_names):
    """After any statement that may set an exit flag, wrap the REST of the
    list in `if not (flag or ...):` — recursively inside If arms, so
    post-break code never runs once a flag is up (reference
    break_continue_transformer's BreakContinueTransformer)."""
    def sets_flag(st):
        for sub in ast.walk(st):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name) and t.id in flag_names:
                        return True
        return False

    out = []
    for i, st in enumerate(stmts):
        if isinstance(st, ast.If):
            st = ast.If(test=st.test,
                        body=_guard_tail(st.body, flag_names),
                        orelse=_guard_tail(st.orelse, flag_names))
        elif isinstance(st, ast.While):
            st = ast.While(test=st.test,
                           body=_guard_tail(st.body, flag_names),
                           orelse=st.orelse)
        elif isinstance(st, ast.For):
            st = ast.For(target=st.target, iter=st.iter,
                         body=_guard_tail(st.body, flag_names),
                         orelse=st.orelse)
        out.append(st)
        if sets_flag(st):
            rest = _guard_tail(stmts[i + 1:], flag_names)
            if rest:
                cond = _name(flag_names[0])
                for fn_ in flag_names[1:]:
                    cond = _or(cond, _name(fn_))
                out.append(ast.If(test=_not(cond), body=rest, orelse=[]))
            return out
    return out


class _ContainerRewriter(ast.NodeTransformer):
    """Functionalize container mutation INSIDE compound statements
    (reference list_transformer.py / the dict-assignment handling in
    basic_api_transformer.py): ``x.append(v)`` →
    ``x = __jst_list_append(x, v)``; ``x[k] = v`` →
    ``x = __jst_setitem(x, k, v)``; ``x[k] op= v`` →
    ``x = __jst_setitem(x, k, x[k] op v)``.  Top-level statements keep
    true Python mutation semantics (they never ride a carry), which also
    bounds the aliasing caveat to converted control flow.  Slice stores
    (``x[a:b] = v``) are left alone."""

    def __init__(self):
        self._depth = 0
        self._key_uid = 0

    def _compound(self, node):
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1
        return node

    visit_If = visit_While = visit_For = _compound

    def visit_FunctionDef(self, node):
        return node  # nested defs own their scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node

    def visit_Expr(self, node):
        c = node.value
        if (self._depth and isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "append"
                and isinstance(c.func.value, ast.Name)
                and len(c.args) == 1 and not c.keywords):
            n = c.func.value.id
            return ast.copy_location(
                _assign(n, _call("__jst_list_append", _name(n), c.args[0])),
                node)
        return node

    def visit_Assign(self, node):
        if (self._depth and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
                and not isinstance(node.targets[0].slice, ast.Slice)):
            t = node.targets[0]
            return ast.copy_location(
                _assign(t.value.id,
                        _call("__jst_setitem", _name(t.value.id), t.slice,
                              node.value)), node)
        return node

    def visit_AugAssign(self, node):
        if (self._depth and isinstance(node.target, ast.Subscript)
                and isinstance(node.target.value, ast.Name)
                and not isinstance(node.target.slice, ast.Slice)):
            t = node.target
            n = t.value.id
            # python evaluates the subscript of an augmented assignment
            # ONCE — `d[next(it)] += 1` must not consume two iterator
            # elements.  Constants and bare names are re-evaluation-safe
            # (and binding them to a temp would push a possibly-str key
            # into the loop carry, which lax.while_loop rejects); any
            # other key expression is bound to a temp first.
            if isinstance(t.slice, (ast.Constant, ast.Name)):
                import copy as _copy
                key_load = _copy.deepcopy(t.slice)
                key_store = t.slice
                bind = []
            else:
                self._key_uid += 1
                key = f"__jst_key_{self._key_uid}"
                bind = [ast.copy_location(_assign(key, t.slice), node)]
                key_load = _name(key)
                key_store = _name(key)
            load = ast.Subscript(value=_name(n), slice=key_load,
                                 ctx=ast.Load())
            newv = ast.BinOp(left=load, op=node.op, right=node.value)
            setit = ast.copy_location(
                _assign(n, _call("__jst_setitem", _name(n), key_store,
                                 newv)), node)
            return bind + [setit]
        return node


class _ReturnRewriter(ast.NodeTransformer):
    """Function-level pass: turn every `return expr` into
    `__jst_ret = True; __jst_rv = expr`, guard following statements, and
    make every loop test include `not __jst_ret` (reference
    return_transformer.py).  Applied only when some return sits inside a
    compound statement (a plain trailing return needs nothing)."""

    RET, RV = "__jst_ret", "__jst_rv"

    def visit_Return(self, node):
        # rv BEFORE the flag: _guard_tail guards everything after the
        # first flag-set statement, and the value assignment must not be
        # swallowed by its own guard
        value = node.value if node.value is not None else ast.Constant(None)
        return [_assign(self.RV, value),
                _assign(self.RET, ast.Constant(True))]

    def visit_FunctionDef(self, node):
        return node  # nested defs own their returns

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


def _rewrite_returns(fdef):
    """Apply the return rewrite when any return is non-trivially placed."""
    nested = any(
        _contains_here([st], (ast.Return,))
        for st in fdef.body
        if isinstance(st, (ast.If, ast.While, ast.For, ast.Try, ast.With)))
    if not nested:
        return
    rw = _ReturnRewriter()
    fdef.body = [rw.visit(st) for st in fdef.body]
    # flatten lists the Return rewrite produced
    flat = []
    for st in fdef.body:
        flat.extend(st if isinstance(st, list) else [st])
    body = _guard_tail(flat, [_ReturnRewriter.RET])
    prologue = [_assign(_ReturnRewriter.RET, ast.Constant(False)),
                _assign(_ReturnRewriter.RV, ast.Constant(None))]
    fdef.body = prologue + body + [
        ast.Return(value=_name(_ReturnRewriter.RV))]


def _rewrite_break_continue(node, uid):
    """Rewrite a While body's break/continue into guarded flags; returns
    (init_stmts, new_body, new_test)."""
    has_brk = _contains_here(node.body, (ast.Break,), through_loops=False)
    has_cont = _contains_here(node.body, (ast.Continue,),
                              through_loops=False)
    if not (has_brk or has_cont):
        return [], node.body, node.test
    brk = f"__jst_brk_{uid}"
    cont = f"__jst_cont_{uid}"
    rw = _BreakContinueRewriter(brk, cont)
    body = []
    for st in node.body:
        new = rw.visit(st)
        body.extend(new if isinstance(new, list) else [new])
    flags = [f for f, used in ((brk, True), (cont, has_cont)) if used]
    body = _guard_tail(body, flags)
    # both flags need PRE-loop values too: they ride the while carry, and
    # a traced while_loop needs a stable carry type from iteration zero
    init = [_assign(brk, ast.Constant(False))]
    prologue = []
    if has_cont:
        init.append(_assign(cont, ast.Constant(False)))
        prologue = [_assign(cont, ast.Constant(False))]
    test = _and(node.test, _not(_name(brk)))
    return init, prologue + body, test


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _fresh(self, base):
        self._uid += 1
        return f"__jst_{base}_{self._uid}"

    # -- if -> cond_call -----------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        assigned = _assigned_names(node.body + node.orelse)
        if not assigned:
            return node  # side-effect-free on locals: keep as-is (eager
            # semantics; traced conditions without assignment are rare).
            # NOTE a def/class statement in such a branch stays plain
            # Python too: fine under a concrete condition; under a traced
            # one the generic TracerBoolConversionError surfaces.  A def
            # whose NAME is read after a CONVERTED if fails at the use
            # site with NameError — function values cannot ride a
            # lax.cond carry; define variants before the if instead.
        _check_no_flow_escape(node.body + node.orelse, "if")
        tname = self._fresh("true")
        fname = self._fresh("false")
        t_assigned = set(_assigned_names(node.body))
        f_assigned = set(_assigned_names(node.orelse))
        carry_name = self._fresh("ifcarry")

        # branch fns receive the current values of every assigned name as
        # a tuple (read-then-write names would otherwise hit python's
        # local-shadowing UnboundLocalError inside the nested function)
        unpack = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Name(id=carry_name, ctx=ast.Load()))
        ret = ast.Return(value=_names_tuple(assigned, ast.Load))
        true_def = ast.FunctionDef(
            name=tname, args=_onearg(carry_name),
            body=[unpack] + node.body + [ret], decorator_list=[])
        false_body = [unpack] + (node.orelse or [ast.Pass()]) + [ret]
        false_def = ast.FunctionDef(
            name=fname, args=_onearg(carry_name), body=false_body,
            decorator_list=[])
        # operand tuple: outer value of each name, or UNDEF when unbound
        operands = ast.Tuple(
            elts=[ast.Call(
                func=ast.Name(id="__jst_undef_lookup", ctx=ast.Load()),
                args=[ast.Lambda(args=_noargs(),
                                 body=ast.Name(id=n, ctx=ast.Load()))],
                keywords=[]) for n in assigned],
            ctx=ast.Load())
        # a name's incoming value matters when some branch might not write
        # it, OR when a branch reads it before its first store in that
        # branch (an UNDEF placeholder could then be silently computed on)
        rbs = _read_before_store(node.body) | _read_before_store(node.orelse)
        needed = ast.Tuple(
            elts=[ast.Constant(not (n in t_assigned and n in f_assigned)
                               or n in rbs)
                  for n in assigned],
            ctx=ast.Load())
        call = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__jst_cond_call", ctx=ast.Load()),
                args=[node.test, ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()), operands,
                      needed],
                keywords=[]))
        out = [true_def, false_def, call]
        # restore python unbound semantics: a name that came back UNDEF
        # (one-armed if on the untaken path, nothing outer) is deleted
        for n in assigned:
            if n not in t_assigned or n not in f_assigned:
                out.append(ast.If(
                    test=ast.Compare(
                        left=ast.Name(id=n, ctx=ast.Load()),
                        ops=[ast.Is()],
                        comparators=[ast.Name(id="__jst_UNDEF",
                                              ctx=ast.Load())]),
                    body=[ast.Delete(targets=[
                        ast.Name(id=n, ctx=ast.Del())])],
                    orelse=[]))
        return out

    # -- while -> while_call -------------------------------------------------
    def visit_While(self, node):
        if node.orelse:
            raise _Unsupported("dy2static: while/else is not supported")
        # a body that can set the early-return flag must stop the loop —
        # applied HERE (not in _ReturnRewriter) so for-range loops, which
        # only become While at conversion time, get the same exit test
        ret = _ReturnRewriter.RET
        if any(isinstance(sub, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == ret
                for t in sub.targets)
               for st in node.body for sub in ast.walk(st)):
            node = ast.While(test=_and(node.test, _not(_name(ret))),
                             body=node.body, orelse=node.orelse)
        # rewrite THIS loop's break/continue into guarded flags before
        # any conversion (reference break_continue_transformer.py); the
        # guard ifs it introduces are then converted like user ifs
        self._uid += 1
        bc_init, bc_body, bc_test = _rewrite_break_continue(node, self._uid)
        node = ast.While(test=bc_test, body=bc_body, orelse=[])
        self.generic_visit(node)
        _check_no_flow_escape(node.body, "while")
        # carry = every var the body assigns (the test reads them through
        # the carry, not a stale closure)
        carried = _assigned_names(node.body)
        if not carried:
            return bc_init + [node] if bc_init else node
        carry_name = self._fresh("carry")
        unpack = ast.Assign(
            targets=[_names_tuple(carried, ast.Store)],
            value=ast.Name(id=carry_name, ctx=ast.Load()))
        cname = self._fresh("while_cond")
        bname = self._fresh("while_body")
        cond_def = ast.FunctionDef(
            name=cname, args=_onearg(carry_name),
            body=[unpack, ast.Return(value=node.test)],
            decorator_list=[])
        body_def = ast.FunctionDef(
            name=bname, args=_onearg(carry_name),
            body=[unpack] + node.body
            + [ast.Return(value=_names_tuple(carried, ast.Load))],
            decorator_list=[])
        init_carry = ast.Tuple(
            elts=[ast.Call(
                func=ast.Name(id="__jst_undef_lookup", ctx=ast.Load()),
                args=[ast.Lambda(args=_noargs(),
                                 body=ast.Name(id=n, ctx=ast.Load()))],
                keywords=[]) for n in carried],
            ctx=ast.Load())
        # a carried name written before any read in the body never needs
        # its pre-loop value — mark it seedable so while_call can give a
        # typed placeholder when it is unbound at loop entry (nested
        # loops' induction/flag temps live in the enclosing body)
        rbs = _read_before_store(node.body)
        seedable = ast.Tuple(
            elts=[ast.Constant(n not in rbs) for n in carried],
            ctx=ast.Load())
        call = ast.Assign(
            targets=[_names_tuple(carried, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__jst_while_call", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      init_carry, seedable],
                keywords=[]))
        return bc_init + [cond_def, body_def, call]

    # -- for i in range(...) -> while ---------------------------------------
    def visit_For(self, node):
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and isinstance(node.target, ast.Name))
        if not is_range or node.orelse:
            self.generic_visit(node)
            return node  # non-range iteration stays Python (unrolled
            # under trace — reference does the same for non-tensor iters)
        i = node.target.id
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        else:
            start, stop, step = rargs
        stop_name = self._fresh("stop")
        step_name = self._fresh("step")
        init = [
            ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                       value=start),
            ast.Assign(targets=[ast.Name(id=stop_name, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_name, ctx=ast.Store())],
                       value=step),
        ]
        test = ast.Call(
            func=ast.Name(id="__jst_range_cont", ctx=ast.Load()),
            args=[ast.Name(id=i, ctx=ast.Load()),
                  ast.Name(id=stop_name, ctx=ast.Load()),
                  ast.Name(id=step_name, ctx=ast.Load())],
            keywords=[])
        incr = ast.AugAssign(target=ast.Name(id=i, ctx=ast.Store()),
                             op=ast.Add(),
                             value=ast.Name(id=step_name, ctx=ast.Load()))
        # rewrite break/continue against THIS loop before appending the
        # increment: `continue` must skip the rest of the body but still
        # advance the induction variable (python range semantics)
        self._uid += 1
        bc_init, bc_body, bc_test = _rewrite_break_continue(
            ast.While(test=test, body=node.body, orelse=[]), self._uid)
        loop = ast.While(test=bc_test, body=bc_body + [incr], orelse=[])
        for n in init:
            ast.copy_location(n, node)
        ast.copy_location(loop, node)
        ast.fix_missing_locations(loop)
        rewritten = self.visit_While(loop)
        out = list(init) + list(bc_init)
        out.extend(rewritten if isinstance(rewritten, list) else [rewritten])
        return out


def _noargs():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _onearg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def convert_to_static(fn):
    """AST-rewrite fn's data-dependent control flow (reference
    StaticFunction's transformer pipeline).  Returns the rewritten
    function, or fn unchanged when no source is available (lambdas,
    builtins, C functions)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    # drop decorators (they already ran to produce this call)
    fdef.decorator_list = []
    # returns nested in compound statements become flag+value assignments
    # (reference return_transformer.py) BEFORE control-flow conversion, so
    # the introduced guards convert like user ifs
    _rewrite_returns(fdef)
    # container mutations inside compounds become functional re-assigns
    # BEFORE control-flow conversion, so containers join branch/loop
    # carries like any assigned name (applied to the BODY — the
    # transformer's visit_FunctionDef guard is for nested defs)
    _crw = _ContainerRewriter()
    fdef.body = [_crw.visit(st) for st in fdef.body]
    ast.fix_missing_locations(fdef)
    new = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new)

    glb = dict(fn.__globals__)
    glb["__jst_cond_call"] = cond_call
    glb["__jst_while_call"] = while_call
    glb["__jst_undef_lookup"] = undef_lookup
    glb["__jst_UNDEF"] = UNDEF
    glb["__jst_range_cont"] = range_cont
    glb["__jst_not"] = bool_not
    glb["__jst_and"] = bool_and
    glb["__jst_or"] = bool_or
    glb["__jst_list_append"] = list_append
    glb["__jst_setitem"] = container_setitem
    # snapshot closure cells (the recompiled fn has no closure)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                pass  # not-yet-filled cell (e.g. the fn's own recursion)
    code = compile(new, filename=f"<dy2static {fn.__name__}>", mode="exec")
    ns = {}
    exec(code, glb, ns)  # noqa: S102 — user's own source, rewritten
    out = ns[fdef.name]
    out.__wrapped_original__ = fn
    return functools.wraps(fn)(out)
