"""dy2static: AST capture of data-dependent Python control flow.

Reference parity: python/paddle/jit/dy2static (program_translator.py:305
+ the transformer pipeline: ifelse_transformer, loop_transformer, ...) —
15k LoC rewriting dygraph Python into static-graph ops.  TPU-native: the
target isn't a ProgramDesc but jaxpr — ``if``/``while``/``for-range``
statements become calls to runtime helpers that pick plain Python when
the condition is concrete (eager) and ``lax.cond`` / ``lax.while_loop``
when it is traced (inside jit), so ONE source serves both modes.

Supported: If / While / for-over-range with single-name assignments in
the rewritten blocks.  Unsupported constructs (return/break/continue
inside converted blocks) raise a clear error at conversion time, like
the reference's transformer diagnostics.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax

__all__ = ["convert_to_static", "cond_call", "while_call",
           "UNDEF", "undef_lookup"]


# ---------------------------------------------------------------- runtime

def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _concrete_bool(x):
    if hasattr(x, "_data"):
        x = x._data
    return bool(x)


class _Undef:
    """Sentinel for a name assigned in only one branch and unbound in
    the other (reference dy2static's UndefinedVar)."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undef()


def undef_lookup(thunk):
    """Read a possibly-unbound outer name: its value, or UNDEF."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def cond_call(pred, true_fn, false_fn, operands, needed):
    """if-statement runtime: python branch when concrete, lax.cond when
    traced.  Branch fns take the tuple of current values of every name
    the branches assign (UNDEF where unbound) and return the updated
    tuple; ``needed[i]`` marks operands whose INCOMING value matters
    (names not re-assigned by both branches)."""
    raw = pred._data if hasattr(pred, "_data") else pred
    if not _is_traced(raw):
        return true_fn(operands) if _concrete_bool(raw) \
            else false_fn(operands)
    fixed = []
    for v, need in zip(operands, needed):
        if v is UNDEF:
            if need:
                raise TypeError(
                    "dy2static: a variable assigned in only one branch of "
                    "a TRACED `if` has no prior definition; initialise it "
                    "before the if so both branches agree on its type")
            # both branches overwrite it: a placeholder keeps lax.cond's
            # operand pytree valid, the incoming value is never used
            fixed.append(jax.numpy.zeros(()))
        else:
            fixed.append(v)
    try:
        return jax.lax.cond(raw, true_fn, false_fn, tuple(fixed))
    except TypeError as e:
        raise TypeError(
            "dy2static: the branches of a TRACED `if` must bind the same "
            "variables with matching shapes/dtypes") from e


def range_cont(i, stop, step):
    """Continuation test for a rewritten for-range: sign-aware."""
    import jax.numpy as jnp
    raw = step._data if hasattr(step, "_data") else step
    if not _is_traced(raw):
        return i < stop if _concrete_bool(raw > 0) else i > stop
    return jnp.where(raw > 0, i < stop, i > stop)


def while_call(cond_fn, body_fn, carry):
    """while-statement runtime: carry is the tuple of loop variables
    (UNDEF entries are body-local temps with no pre-loop value)."""
    first = cond_fn(carry)
    raw = first._data if hasattr(first, "_data") else first
    if not _is_traced(raw) and not any(
            _is_traced(v._data if hasattr(v, "_data") else v)
            for v in jax.tree.leaves(carry)):
        while _concrete_bool(cond_fn(carry)):
            carry = body_fn(carry)
        return carry

    if any(v is UNDEF for v in carry):
        raise TypeError(
            "dy2static: a TRACED `while` body introduces a variable with "
            "no pre-loop value; initialise it before the loop so the "
            "carry has a stable type")

    def cond_raw(c):
        out = cond_fn(c)
        return out._data if hasattr(out, "_data") else out

    return jax.lax.while_loop(cond_raw, body_fn, carry)


# ------------------------------------------------------------ the rewrite

class _Unsupported(NotImplementedError):
    pass


def _assigned_names(nodes):
    """Simple-Name store targets in a statement list (recursively)."""
    out = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Store) and node.id not in out:
                out.append(node.id)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) and \
                    node.target.id not in out:
                out.append(node.target.id)
            self.generic_visit(node)

        # nested defs own their scope
        def visit_FunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        # py3 comprehension targets are scoped to the comprehension — they
        # are NOT branch-local assignments (walrus escapes are not handled)
        def _skip(self, node):
            pass

        visit_ListComp = visit_SetComp = _skip
        visit_GeneratorExp = visit_DictComp = _skip

    for n in nodes:
        V().visit(n)
    return out


def _read_before_store(nodes):
    """Names Loaded before their first Store, in (approximate) execution
    order — an UNDEF placeholder for such a name could actually be read,
    so it must be treated as `needed` (loud error instead of a silent 0)."""
    stored = set()
    reads = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load) and node.id not in stored:
                reads.add(node.id)
            elif isinstance(node.ctx, ast.Store):
                stored.add(node.id)

        def visit_Assign(self, node):  # value is evaluated before targets
            self.visit(node.value)
            for t in node.targets:
                self.visit(t)

        def visit_AugAssign(self, node):  # target is read, then written
            self.visit(node.value)
            if isinstance(node.target, ast.Name):
                if node.target.id not in stored:
                    reads.add(node.target.id)
                stored.add(node.target.id)
            else:
                self.visit(node.target)

        def visit_Call(self, node):
            # __jst_undef_lookup(lambda: name) is the transformer's OWN
            # safe read (returns UNDEF instead of raising) — not a user
            # read; skip it so already-rewritten inner ifs don't mark
            # every assigned name as read-before-store
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "__jst_undef_lookup":
                return
            self.generic_visit(node)

        def _visit_comp(self, node):
            # a comprehension's generators run before its elt, and its
            # targets are scoped to it — visit in execution order with the
            # targets counting as stores (conservatively left in `stored`)
            for gen in node.generators:
                self.visit(gen.iter)
                self.visit(gen.target)
                for cond in gen.ifs:
                    self.visit(cond)
            if hasattr(node, "elt"):
                self.visit(node.elt)
            else:  # DictComp
                self.visit(node.key)
                self.visit(node.value)

        visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp
        visit_DictComp = _visit_comp

        def _visit_closure(self, node):
            # free variables of a nested def/lambda may be read when it is
            # called — count its Loads (minus its own args) as reads.
            # Functions the transformer itself generated (rewritten inner
            # ifs/whiles) are exempt: their reads go through the carry
            # tuple / undef_lookup machinery, not bare unbound names.
            if getattr(node, "name", "").startswith("__jst_"):
                return
            args = {a.arg for a in node.args.args + node.args.posonlyargs
                    + node.args.kwonlyargs}
            for a in (node.args.vararg, node.args.kwarg):
                if a is not None:
                    args.add(a.arg)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id not in args and sub.id not in stored:
                    reads.add(sub.id)

        visit_FunctionDef = visit_AsyncFunctionDef = _visit_closure
        visit_Lambda = _visit_closure

    v = V()
    for n in nodes:
        v.visit(n)
    return reads


def _read_names(nodes):
    out = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, ast.Load):
                out.add(node.id)

    for n in nodes:
        V().visit(n)
    return out


def _check_no_flow_escape(nodes, what):
    class V(ast.NodeVisitor):
        def visit_Return(self, node):
            raise _Unsupported(
                f"dy2static: `return` inside a converted {what} is not "
                "supported; assign to a variable and return after it")

        def visit_Break(self, node):
            raise _Unsupported(
                f"dy2static: `break` inside a converted {what} is not "
                "supported; fold the exit condition into the loop test")

        def visit_Continue(self, node):
            raise _Unsupported(
                f"dy2static: `continue` inside a converted {what} is not "
                "supported")

        def visit_FunctionDef(self, node):
            pass

    for n in nodes:
        V().visit(n)


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _fresh(self, base):
        self._uid += 1
        return f"__jst_{base}_{self._uid}"

    # -- if -> cond_call -----------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        assigned = _assigned_names(node.body + node.orelse)
        if not assigned:
            return node  # side-effect-free on locals: keep as-is (eager
            # semantics; traced conditions without assignment are rare)
        _check_no_flow_escape(node.body + node.orelse, "if")
        tname = self._fresh("true")
        fname = self._fresh("false")
        t_assigned = set(_assigned_names(node.body))
        f_assigned = set(_assigned_names(node.orelse))
        carry_name = self._fresh("ifcarry")

        # branch fns receive the current values of every assigned name as
        # a tuple (read-then-write names would otherwise hit python's
        # local-shadowing UnboundLocalError inside the nested function)
        unpack = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Name(id=carry_name, ctx=ast.Load()))
        ret = ast.Return(value=_names_tuple(assigned, ast.Load))
        true_def = ast.FunctionDef(
            name=tname, args=_onearg(carry_name),
            body=[unpack] + node.body + [ret], decorator_list=[])
        false_body = [unpack] + (node.orelse or [ast.Pass()]) + [ret]
        false_def = ast.FunctionDef(
            name=fname, args=_onearg(carry_name), body=false_body,
            decorator_list=[])
        # operand tuple: outer value of each name, or UNDEF when unbound
        operands = ast.Tuple(
            elts=[ast.Call(
                func=ast.Name(id="__jst_undef_lookup", ctx=ast.Load()),
                args=[ast.Lambda(args=_noargs(),
                                 body=ast.Name(id=n, ctx=ast.Load()))],
                keywords=[]) for n in assigned],
            ctx=ast.Load())
        # a name's incoming value matters when some branch might not write
        # it, OR when a branch reads it before its first store in that
        # branch (an UNDEF placeholder could then be silently computed on)
        rbs = _read_before_store(node.body) | _read_before_store(node.orelse)
        needed = ast.Tuple(
            elts=[ast.Constant(not (n in t_assigned and n in f_assigned)
                               or n in rbs)
                  for n in assigned],
            ctx=ast.Load())
        call = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__jst_cond_call", ctx=ast.Load()),
                args=[node.test, ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()), operands,
                      needed],
                keywords=[]))
        out = [true_def, false_def, call]
        # restore python unbound semantics: a name that came back UNDEF
        # (one-armed if on the untaken path, nothing outer) is deleted
        for n in assigned:
            if n not in t_assigned or n not in f_assigned:
                out.append(ast.If(
                    test=ast.Compare(
                        left=ast.Name(id=n, ctx=ast.Load()),
                        ops=[ast.Is()],
                        comparators=[ast.Name(id="__jst_UNDEF",
                                              ctx=ast.Load())]),
                    body=[ast.Delete(targets=[
                        ast.Name(id=n, ctx=ast.Del())])],
                    orelse=[]))
        return out

    # -- while -> while_call -------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            raise _Unsupported("dy2static: while/else is not supported")
        _check_no_flow_escape(node.body, "while")
        # carry = every var the body assigns (the test reads them through
        # the carry, not a stale closure)
        carried = _assigned_names(node.body)
        if not carried:
            return node
        carry_name = self._fresh("carry")
        unpack = ast.Assign(
            targets=[_names_tuple(carried, ast.Store)],
            value=ast.Name(id=carry_name, ctx=ast.Load()))
        cname = self._fresh("while_cond")
        bname = self._fresh("while_body")
        cond_def = ast.FunctionDef(
            name=cname, args=_onearg(carry_name),
            body=[unpack, ast.Return(value=node.test)],
            decorator_list=[])
        body_def = ast.FunctionDef(
            name=bname, args=_onearg(carry_name),
            body=[unpack] + node.body
            + [ast.Return(value=_names_tuple(carried, ast.Load))],
            decorator_list=[])
        init_carry = ast.Tuple(
            elts=[ast.Call(
                func=ast.Name(id="__jst_undef_lookup", ctx=ast.Load()),
                args=[ast.Lambda(args=_noargs(),
                                 body=ast.Name(id=n, ctx=ast.Load()))],
                keywords=[]) for n in carried],
            ctx=ast.Load())
        call = ast.Assign(
            targets=[_names_tuple(carried, ast.Store)],
            value=ast.Call(
                func=ast.Name(id="__jst_while_call", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      init_carry],
                keywords=[]))
        return [cond_def, body_def, call]

    # -- for i in range(...) -> while ---------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and isinstance(node.target, ast.Name))
        if not is_range or node.orelse:
            return node  # non-range iteration stays Python (unrolled
            # under trace — reference does the same for non-tensor iters)
        _check_no_flow_escape(node.body, "for")
        i = node.target.id
        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        else:
            start, stop, step = rargs
        stop_name = self._fresh("stop")
        step_name = self._fresh("step")
        init = [
            ast.Assign(targets=[ast.Name(id=i, ctx=ast.Store())],
                       value=start),
            ast.Assign(targets=[ast.Name(id=stop_name, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_name, ctx=ast.Store())],
                       value=step),
        ]
        test = ast.Call(
            func=ast.Name(id="__jst_range_cont", ctx=ast.Load()),
            args=[ast.Name(id=i, ctx=ast.Load()),
                  ast.Name(id=stop_name, ctx=ast.Load()),
                  ast.Name(id=step_name, ctx=ast.Load())],
            keywords=[])
        incr = ast.AugAssign(target=ast.Name(id=i, ctx=ast.Store()),
                             op=ast.Add(),
                             value=ast.Name(id=step_name, ctx=ast.Load()))
        loop = ast.While(test=test, body=node.body + [incr], orelse=[])
        for n in init:
            ast.copy_location(n, node)
        ast.copy_location(loop, node)
        rewritten = self.visit_While(loop)
        out = list(init)
        out.extend(rewritten if isinstance(rewritten, list) else [rewritten])
        return out


def _noargs():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _onearg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def convert_to_static(fn):
    """AST-rewrite fn's data-dependent control flow (reference
    StaticFunction's transformer pipeline).  Returns the rewritten
    function, or fn unchanged when no source is available (lambdas,
    builtins, C functions)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    # drop decorators (they already ran to produce this call)
    fdef.decorator_list = []
    new = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new)

    glb = dict(fn.__globals__)
    glb["__jst_cond_call"] = cond_call
    glb["__jst_while_call"] = while_call
    glb["__jst_undef_lookup"] = undef_lookup
    glb["__jst_UNDEF"] = UNDEF
    glb["__jst_range_cont"] = range_cont
    # snapshot closure cells (the recompiled fn has no closure)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                pass  # not-yet-filled cell (e.g. the fn's own recursion)
    code = compile(new, filename=f"<dy2static {fn.__name__}>", mode="exec")
    ns = {}
    exec(code, glb, ns)  # noqa: S102 — user's own source, rewritten
    out = ns[fdef.name]
    out.__wrapped_original__ = fn
    return functools.wraps(fn)(out)
