"""jit.save / jit.load — deployable compiled artifacts.

Reference parity: ``paddle.jit.save`` (jit/api.py) writes ``model.pdmodel``
(ProgramDesc) + ``model.pdiparams``; ``paddle.jit.load`` returns a
``TranslatedLayer``; the C++ serving side loads the same artifact
(fluid/inference/io.cc, fluid/jit/serializer.cc).

TPU-native artifact: StableHLO.  ``save`` traces the Layer's forward with
parameters as constants-free inputs, serializes via ``jax.export``
(portable StableHLO bytes) alongside the parameters (npz) and a JSON meta —
``<path>.pdmodel`` (stablehlo), ``<path>.pdiparams`` (npz),
``<path>.pdmeta`` (json).  ``load`` restores a ``TranslatedLayer`` that
runs the deserialized executable; the native predictor shim (csrc/) reads
the same files.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Sequence

import numpy as np

__all__ = ["save", "load", "InputSpec", "TranslatedLayer"]


class InputSpec:
    """Reference ``paddle.static.InputSpec`` parity."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def has_dynamic_dims(self) -> bool:
        return any(d is None or (isinstance(d, int) and d < 0)
                   for d in self.shape)

    def to_sds(self, scope=None, name_hint="x"):
        """Static dims → ShapeDtypeStruct directly; None/-1 dims become
        jax.export symbolic dimensions so the saved artifact accepts any
        size there (reference: save_inference_model supports dynamic batch).
        Axis-0 symbols are all named "batch" so every input shares one
        batch dimension; pass a common `scope` across specs."""
        import jax
        from paddle_tpu.core.dtypes import to_jax
        if not self.has_dynamic_dims():
            return jax.ShapeDtypeStruct(tuple(self.shape),
                                        to_jax(self.dtype))
        from jax import export as jexport
        if scope is None:
            scope = jexport.SymbolicScope()
        parts = []
        for i, d in enumerate(self.shape):
            if d is None or (isinstance(d, int) and d < 0):
                parts.append("batch" if i == 0 else f"{name_hint}_d{i}")
            else:
                parts.append(str(d))
        shape = jexport.symbolic_shape(", ".join(parts), scope=scope)
        return jax.ShapeDtypeStruct(shape, to_jax(self.dtype))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def save(layer, path: str, input_spec: Optional[List] = None, **configs):
    """Serialize `layer` (or a to_static-wrapped fn) for inference."""
    import jax
    from jax import export as jexport
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.nn.layer import Layer

    target = getattr(layer, "__wrapped__", layer)
    if not isinstance(target, Layer):
        raise TypeError("jit.save expects a Layer (or to_static(Layer))")

    if input_spec is None:
        raise ValueError("jit.save on TPU requires input_spec (shapes are "
                         "compiled; provide InputSpec/example tensors)")
    sds = []
    sym_scope = None
    for i, spec in enumerate(input_spec):
        if isinstance(spec, InputSpec):
            if spec.has_dynamic_dims() and sym_scope is None:
                from jax import export as jexport
                sym_scope = jexport.SymbolicScope()
            sds.append(spec.to_sds(scope=sym_scope, name_hint=f"x{i}"))
        elif hasattr(spec, "_data"):
            sds.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                            spec._data.dtype))
        else:
            arr = np.asarray(spec)
            sds.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))

    params = params_of(target)
    param_names = sorted(params)

    def pure(params_tuple, *inputs):
        pdict = dict(zip(param_names, params_tuple))
        out = functional_call(target, pdict, *inputs)
        return jax.tree.map(
            lambda t: t._data if hasattr(t, "_data") else t, out,
            is_leaf=lambda t: hasattr(t, "_data"))

    params_sds = tuple(jax.ShapeDtypeStruct(params[n].shape,
                                            params[n].dtype)
                       for n in param_names)
    exp = jexport.export(jax.jit(pure))(params_sds, *sds)

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    # raw StableHLO for the native predictor (csrc/predictor): PJRT
    # compiles this text directly, no jax at serving time
    with open(path + ".pdstablehlo", "w") as f:
        f.write(exp.mlir_module())
    np.savez(path + ".pdiparams",
             **{n: np.asarray(params[n]) for n in param_names})
    input_names = []
    for i, spec in enumerate(input_spec):
        name = getattr(spec, "name", None)
        input_names.append(name if name else f"x{i}")
    meta = {
        "format": "stablehlo-jax-export-v1",
        "param_names": param_names,
        "input_names": input_names,
        "inputs": [{"shape": [d if isinstance(d, int) else str(d)
                              for d in s.shape],
                    "dtype": str(s.dtype)}
                   for s in sds],
        "mlir_preview": exp.mlir_module()[:2000],
    }
    with open(path + ".pdmeta", "w") as f:
        json.dump(meta, f, indent=2)
    return path


class TranslatedLayer:
    """Loaded inference layer (reference TranslatedLayer,
    jit/translated_layer.py): call like the original Layer."""

    def __init__(self, exported, params_tuple, meta):
        self._exported = exported
        self._params = params_tuple
        self._meta = meta

    def __call__(self, *inputs):
        import jax.numpy as jnp
        from paddle_tpu.core.dispatch import wrap_like
        raw = tuple(jnp.asarray(
            x._data if hasattr(x, "_data") else np.asarray(x))
            for x in inputs)
        out = self._exported.call(self._params, *raw)
        import jax
        return jax.tree.map(wrap_like, out)

    forward = __call__

    def eval(self):
        return self

    @property
    def input_specs(self):
        return self._meta["inputs"]


def load(path: str) -> TranslatedLayer:
    import jax.numpy as jnp
    from jax import export as jexport
    with open(path + ".pdmodel", "rb") as f:
        exp = jexport.deserialize(f.read())
    with open(path + ".pdmeta") as f:
        meta = json.load(f)
    archive = np.load(path + ".pdiparams.npz"
                      if os.path.exists(path + ".pdiparams.npz")
                      else path + ".pdiparams")
    params = tuple(jnp.asarray(archive[n]) for n in meta["param_names"])
    return TranslatedLayer(exp, params, meta)
