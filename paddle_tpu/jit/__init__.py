"""paddle_tpu.jit — compiled execution.

Reference parity: python/paddle/jit (@to_static AST rewriting →
ConcreteProgram → run_program op, jit/dy2static/program_translator.py:305).
TPU-native: Layers are already functional through
core.functional.functional_call, so "static mode" is jax.jit over the pure
form — `to_static(layer_or_fn)` returns a compiled callable with no source
rewriting, and TrainStep compiles a whole fwd+bwd+update step.

Program analysis: every compiled callable carries a
``_signature_monitor`` (analysis/recompile.py) that, when monitoring is
on, records call signatures so the recompile-hazard pass can flag
executable-cache churn; ``analyze="warn"|"strict"`` (or the
``PADDLE_TPU_ANALYZE`` env var) runs the full ``paddle_tpu.analysis``
pass pipeline on the first call.
"""

from __future__ import annotations

import sys

import jax

from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.jit.save_load import (InputSpec, TranslatedLayer, load,
                                      save)

__all__ = ["TrainStep", "to_static", "save", "load", "InputSpec",
           "TranslatedLayer"]


def _coerce_to_specs(args, specs):
    """Honor ``input_spec``: validate each positional arg against its
    spec and coerce it to the spec's dtype (python scalars become
    strongly-typed arrays — which also kills the weak-type recompile
    hazard).  Dims that are None/-1 are free; int dims must match."""
    import jax.numpy as jnp
    from paddle_tpu.core.dtypes import to_jax

    out = list(args)
    for i, spec in enumerate(specs):
        if i >= len(out) or not isinstance(spec, InputSpec):
            continue
        x = out[i]
        raw = x._data if hasattr(x, "_data") else x
        arr = jnp.asarray(raw, to_jax(spec.dtype))
        shape = tuple(arr.shape)
        if len(shape) != len(spec.shape):
            raise ValueError(
                f"to_static: argument {i} has rank {len(shape)}, "
                f"input_spec expects rank {len(spec.shape)} "
                f"(spec {spec}, got shape {shape})")
        for d, (got, want) in enumerate(zip(shape, spec.shape)):
            if want is None or (isinstance(want, int) and want < 0):
                continue
            if got != want:
                raise ValueError(
                    f"to_static: argument {i} dim {d} is {got}, "
                    f"input_spec pins it to {want} (spec {spec})")
        out[i] = arr
    return tuple(out)


def to_static(obj=None, input_spec=None, full_graph=True, analyze=None,
              shardings=None, **kwargs):
    """Decorator/function: compile a Layer's forward or a plain function.

    For a Layer, parameters are captured fresh on every call (so eager
    updates by optimizers stay visible) but the XLA executable is cached by
    shape/dtype, like the reference's ConcreteProgram cache
    (jit/dy2static/program_translator.py).  ``input_spec`` is honored on
    BOTH paths (Layer forward args and plain/dy2static functions):
    arguments are validated and coerced to the spec's dtype before
    tracing.  ``analyze`` opts this callable into the
    ``paddle_tpu.analysis`` pass pipeline on first call ("warn" prints
    findings, "strict" raises on ERROR); default follows
    ``PADDLE_TPU_ANALYZE``.  ``shardings`` accepts an autoshard plan
    (``analysis.autoshard.AutoShardPlan``): for a Layer target, its
    parameters are placed under the plan's NamedShardings before every
    compiled call and array inputs under the plan's batch spec — GSPMD
    propagates the layout from there."""
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.nn.layer import Layer

    plan_sh = plan_batch_sh = None
    if shardings is not None:
        from jax.sharding import NamedSharding
        if hasattr(shardings, "param_specs"):     # AutoShardPlan
            plan_sh = shardings.shardings()
            if shardings.batch_spec is not None:
                plan_batch_sh = NamedSharding(shardings.jax_mesh(),
                                              shardings.batch_spec)
        elif isinstance(shardings, dict):
            plan_sh = dict(shardings)
        else:
            raise TypeError(
                f"shardings= expects an AutoShardPlan or a dict, "
                f"got {type(shardings).__name__}")

    def _place_params(ps):
        if not plan_sh:
            return ps
        return {n: jax.device_put(a, plan_sh[n]) if n in plan_sh else a
                for n, a in ps.items()}

    def _place_input(x):
        if plan_batch_sh is None or not hasattr(x, "ndim") or \
                not getattr(x, "ndim", 0):
            return x
        try:
            return jax.device_put(x, plan_batch_sh)
        except ValueError:            # rank/spec mismatch — leave as-is
            return x

    def wrap(target):
        from paddle_tpu.analysis.recompile import SignatureMonitor
        name = getattr(target, "__name__", type(target).__name__)
        monitor = SignatureMonitor(name=name)
        specs = list(input_spec) if input_spec is not None else None
        state = {"analyzed": False}

        def prepare(a, kw):
            if specs is not None:
                a = _coerce_to_specs(a, specs)
            if monitor.active:
                monitor.record(a, kw)
            return a, kw

        def maybe_analyze(tgt, a, kw):
            from paddle_tpu.analysis import analysis_mode
            mode = analyze if analyze is not None else analysis_mode()
            if not mode or state["analyzed"]:
                return
            state["analyzed"] = True
            import paddle_tpu.analysis as _A
            report = _A.check(tgt, *a, strict=(mode == "strict"), **kw)
            if len(report):
                print(report.format(), file=sys.stderr)

        if not isinstance(target, Layer) and callable(target):
            # AST capture of data-dependent if/while/for-range (reference
            # dy2static transformer pipeline) before tracing
            from paddle_tpu.jit.dy2static import convert_to_static
            target = convert_to_static(target)
        if isinstance(target, Layer):
            jfn = jax.jit(lambda params, *a, **kw: _raw_tree(
                functional_call(target, params, *a, **kw)))

            def call(*a, **kw):
                a, kw = prepare(a, kw)
                maybe_analyze(target, a, kw)
                a = tuple(_place_input(_raw(x)) for x in a)
                kw = {k: _raw(v) for k, v in kw.items()}
                return _wrap_tree(jfn(_place_params(params_of(target)),
                                      *a, **kw))
            call.__wrapped__ = target
            call._signature_monitor = monitor
            return call
        jfn = jax.jit(lambda *a, **kw: _raw_tree(target(*a, **kw)))

        def call(*a, **kw):
            a, kw = prepare(a, kw)
            maybe_analyze(target, a, kw)
            a = tuple(_place_input(_raw(x)) for x in a)
            kw = {k: _raw(v) for k, v in kw.items()}
            return _wrap_tree(jfn(*a, **kw))
        call.__wrapped__ = target
        call._signature_monitor = monitor
        return call

    def _raw(x):
        return x._data if hasattr(x, "_data") else x

    def _raw_tree(tree):
        return jax.tree.map(_raw, tree,
                            is_leaf=lambda t: hasattr(t, "_data"))

    def _wrap_tree(tree):
        from paddle_tpu.core.dispatch import wrap_like
        return jax.tree.map(wrap_like, tree)

    if obj is None:
        return wrap
    return wrap(obj)
