"""paddle_tpu.jit — compiled execution.

Reference parity: python/paddle/jit (@to_static AST rewriting →
ConcreteProgram → run_program op, jit/dy2static/program_translator.py:305).
TPU-native: Layers are already functional through
core.functional.functional_call, so "static mode" is jax.jit over the pure
form — `to_static(layer_or_fn)` returns a compiled callable with no source
rewriting, and TrainStep compiles a whole fwd+bwd+update step.
"""

from __future__ import annotations

import jax

from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.jit.save_load import (InputSpec, TranslatedLayer, load,
                                      save)

__all__ = ["TrainStep", "to_static", "save", "load", "InputSpec",
           "TranslatedLayer"]


def to_static(obj=None, input_spec=None, full_graph=True, **kwargs):
    """Decorator/function: compile a Layer's forward or a plain function.

    For a Layer, parameters are captured fresh on every call (so eager
    updates by optimizers stay visible) but the XLA executable is cached by
    shape/dtype, like the reference's ConcreteProgram cache
    (jit/dy2static/program_translator.py)."""
    from paddle_tpu.core.functional import functional_call, params_of
    from paddle_tpu.nn.layer import Layer

    def wrap(target):
        if not isinstance(target, Layer) and callable(target):
            # AST capture of data-dependent if/while/for-range (reference
            # dy2static transformer pipeline) before tracing
            from paddle_tpu.jit.dy2static import convert_to_static
            target = convert_to_static(target)
        if isinstance(target, Layer):
            jfn = jax.jit(lambda params, *a, **kw: _raw_tree(
                functional_call(target, params, *a, **kw)))

            def call(*a, **kw):
                a = tuple(_raw(x) for x in a)
                kw = {k: _raw(v) for k, v in kw.items()}
                return _wrap_tree(jfn(params_of(target), *a, **kw))
            call.__wrapped__ = target
            return call
        jfn = jax.jit(lambda *a, **kw: _raw_tree(target(*a, **kw)))

        def call(*a, **kw):
            a = tuple(_raw(x) for x in a)
            kw = {k: _raw(v) for k, v in kw.items()}
            return _wrap_tree(jfn(*a, **kw))
        call.__wrapped__ = target
        return call

    def _raw(x):
        return x._data if hasattr(x, "_data") else x

    def _raw_tree(tree):
        return jax.tree.map(_raw, tree,
                            is_leaf=lambda t: hasattr(t, "_data"))

    def _wrap_tree(tree):
        from paddle_tpu.core.dispatch import wrap_like
        return jax.tree.map(wrap_like, tree)

    if obj is None:
        return wrap
    return wrap(obj)
