"""paddle_tpu.optimizer (parity: python/paddle/optimizer/)."""

from paddle_tpu.optimizer import lr  # noqa: F401
from paddle_tpu.optimizer.optimizer import Optimizer  # noqa: F401
from paddle_tpu.optimizer.optimizers import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, RMSProp,
)
