"""Concrete optimizers (parity: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adagrad,rmsprop,adadelta,adamax,lamb}.py).  Pure update rules on
arrays; see optimizer.py for the eager/functional duality."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.optimizer.optimizer import Optimizer, _DecoupledWD

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "RMSProp",
           "Adadelta", "Adamax", "Lamb"]


class SGD(Optimizer):
    def _update(self, p, g, s, lr, step):
        return p - lr * g.astype(p.dtype), s

    def _update_sparse(self, p, g, s, lr, step):
        """Rows-touched scatter-add (reference sgd selected_rows kernel):
        no dense [vocab, d] grad/update buffer exists."""
        if self._weight_decay:
            g = g.coalesce()  # wd must hit each touched row exactly once
            vals = g.values.astype(p.dtype) + \
                self._weight_decay * p[g.rows]
        else:
            vals = g.values.astype(p.dtype)
        return p.at[g.rows].add(-lr * vals), s


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update(self, p, g, s, lr, step):
        g = g.astype(p.dtype)
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        return p - lr * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lazy = bool(lazy_mode)

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p, dtype=jnp.float32)}

    def _sparse_wd(self):
        """L2-into-grad coefficient for the sparse rule (AdamW overrides:
        its decay is decoupled)."""
        return self._weight_decay

    def _decoupled_wd(self):
        return 0.0

    def _update_sparse(self, p, g, s, lr, step):
        """Reference adam selected_rows kernel (lazy_mode toggles whether
        moments decay on untouched rows).  Either way the [vocab, d]
        dense GRADIENT is never built.

        lazy_mode=True: moments + params update ONLY on touched rows —
        O(rows) work, the recommender/embedding-scale fast path.
        lazy_mode=False: full-Adam semantics (moments decay everywhere,
        every row moves by its mhat/vhat) via moment-wide decay plus a
        row scatter of the gradient term."""
        g = g.coalesce()
        r = g.rows
        gf = g.values.astype(jnp.float32)
        if self._sparse_wd():
            gf = gf + self._sparse_wd() * p[r].astype(jnp.float32)
        m, v = s["moment1"], s["moment2"]
        bc1 = 1 - self._beta1 ** step
        bc2 = 1 - self._beta2 ** step
        pf = p.astype(jnp.float32)
        wd = self._decoupled_wd()
        if self._lazy:
            m_r = self._beta1 * m[r] + (1 - self._beta1) * gf
            v_r = self._beta2 * v[r] + (1 - self._beta2) * jnp.square(gf)
            upd = (m_r / bc1) / (jnp.sqrt(v_r / bc2) + self._eps)
            if wd:
                upd = upd + wd * pf[r]
            new_p = pf.at[r].add(-lr * upd).astype(p.dtype)
            return new_p, {"moment1": m.at[r].set(m_r),
                           "moment2": v.at[r].set(v_r)}
        m = (self._beta1 * m).at[r].add((1 - self._beta1) * gf)
        v = (self._beta2 * v).at[r].add((1 - self._beta2) * jnp.square(gf))
        upd = (m / bc1) / (jnp.sqrt(v / bc2) + self._eps)
        if wd:
            upd = upd + wd * pf
        return (pf - lr * upd).astype(p.dtype), {"moment1": m, "moment2": v}

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(gf)
        bc1 = 1 - self._beta1 ** step
        bc2 = 1 - self._beta2 ** step
        mhat = m / bc1
        vhat = v / bc2
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"moment1": m, "moment2": v}


class AdamW(Adam, _DecoupledWD):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._weight_decay = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _sparse_wd(self):
        return 0.0  # decoupled, not folded into the gradient

    def _decoupled_wd(self):
        wd = self._weight_decay
        if wd and self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(self._current_param_name or ""):
            return 0.0
        return wd

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(gf)
        mhat = m / (1 - self._beta1 ** step)
        vhat = v / (1 - self._beta2 ** step)
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = self._decoupled_wd()
        pf = p.astype(jnp.float32)
        pf = pf - lr * (upd + wd * pf)
        return pf.astype(p.dtype), {"moment1": m, "moment2": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps = epsilon
        self._init_val = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_val,
                                        dtype=jnp.float32)}

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        acc = s["moment"] + jnp.square(gf)
        upd = gf / (jnp.sqrt(acc) + self._eps)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p, dtype=jnp.float32),
              "momentum": jnp.zeros_like(p, dtype=jnp.float32)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p, dtype=jnp.float32)
        return st

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        ms = self._rho * s["mean_square"] + (1 - self._rho) * jnp.square(gf)
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * gf
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * s["momentum"] + lr * gf / denom
        out = {"mean_square": ms, "momentum": mom}
        if self._centered:
            out["mean_grad"] = mg
        return (p.astype(jnp.float32) - mom).astype(p.dtype), out


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p, dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(p, dtype=jnp.float32)}

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * \
            jnp.square(gf)
        upd = gf * jnp.sqrt(s["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * \
            jnp.square(upd)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(p, dtype=jnp.float32)}

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        m = self._beta1 * s["moment"] + (1 - self._beta1) * gf
        u = jnp.maximum(self._beta2 * s["inf_norm"], jnp.abs(gf))
        upd = m / ((1 - self._beta1 ** step) * (u + self._eps))
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"moment": m, "inf_norm": u}


class Lamb(Optimizer, _DecoupledWD):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._weight_decay = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p, dtype=jnp.float32)}

    def _update(self, p, g, s, lr, step):
        gf = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(gf)
        mhat = m / (1 - self._beta1 ** step)
        vhat = v / (1 - self._beta2 ** step)
        pf = p.astype(jnp.float32)
        wd = self._weight_decay
        if wd and self._exclude_fn is not None and \
                self._exclude_fn(self._current_param_name or ""):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + self._eps) + wd * pf
        w_norm = jnp.linalg.norm(pf.ravel())
        r_norm = jnp.linalg.norm(r.ravel())
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), \
            {"moment1": m, "moment2": v}
