"""Optimizer base (parity: python/paddle/optimizer/optimizer.py —
accumulators, grad clip, regularization, LR scheduler integration).

TPU-native design: each optimizer defines ONE pure update rule
`_update(param, grad, state, lr, ...) -> (new_param, new_state)` on raw jax
arrays.  The eager `step()` walks Parameter.grad and mutates in place (paddle
semantics); the functional `apply_gradients(params, grads, opt_state)` is the
same rule jitted over pytrees — used by the train-step compiler, pjit
sharding, and the distributed wrappers.  One rule, two execution modes, like
core/dispatch.py for ops.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import dispatch, unwrap
from paddle_tpu.core.tensor import Parameter, Tensor, no_grad

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        from paddle_tpu.optimizer import lr as lr_mod
        self._lr_scheduler = None
        if isinstance(learning_rate, lr_mod.LRScheduler):
            self._lr_scheduler = learning_rate
        else:
            self._base_lr = float(learning_rate)
        self._parameters = list(parameters) if parameters is not None else None
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay-like object with coeff
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[int, Dict[str, Any]] = {}
        self._global_step = 0
        self._current_param_name = None

    # -- LR ------------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return self._base_lr

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError("optimizer's learning rate is a scheduler; "
                               "call scheduler.step()/set attrs instead")
        self._base_lr = float(value)

    # -- update rule (override) ---------------------------------------------
    def _init_state(self, param_arr) -> Dict[str, Any]:
        """Per-parameter state pytree (raw arrays)."""
        return {}

    def _init_state_full(self, param_arr) -> Dict[str, Any]:
        st = self._init_state(param_arr)
        if self._multi_precision and param_arr.dtype in (jnp.bfloat16,
                                                         jnp.float16):
            st = dict(st)
            st["_master"] = param_arr.astype(jnp.float32)
        return st

    def _update(self, param, grad, state, lr, step):
        """Pure rule: arrays in, arrays out. Override in subclasses."""
        raise NotImplementedError

    def _update_sparse(self, param, grad, state, lr, step):
        """Rule for a RowSparseGrad (embedding(sparse=True) — the
        reference's selected_rows kernel slot, phi/kernels/selected_rows/).
        Default: densify (correct for any optimizer); SGD/Adam/AdamW
        override with rows-touched scatter updates that never build the
        [vocab, d] dense gradient.  Weight decay under sparse grads is
        LAZY: it touches only the gradient's rows (reference lazy_mode
        semantics)."""
        return self._update(param, grad.to_dense(), state, lr, step)

    def _apply_weight_decay(self, param, grad):
        """Default: L2 regularization folded into the gradient (reference
        optimizer.py regularization path). AdamW overrides to decoupled."""
        if self._weight_decay:
            return grad + self._weight_decay * param
        return grad

    # -- eager step ----------------------------------------------------------
    def step(self):
        if self._parameters is None:
            raise ValueError("Optimizer created without parameters; pass "
                             "parameters=model.parameters()")
        params_grads = [(p, p.grad) for p in self._parameters
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._global_step += 1
        step = self._global_step
        for i, (p, g) in enumerate(params_grads):
            if g is None:
                continue
            key = id(p)
            if key not in self._accumulators:
                self._accumulators[key] = self._init_state_full(p._data)
            state = self._accumulators[key]
            self._current_param_name = p.name or f"param_{i}"
            new_p, new_state = self._update_with_master(
                p._data, unwrap(g), state, lr, step)
            p._set_data(new_p.astype(p._data.dtype))
            self._accumulators[key] = new_state

    def _update_with_master(self, pv, gv, state, lr, step):
        """Shared by eager and functional paths: optional fp32 master weight
        (kept in the optimizer state under '_master'), weight decay policy,
        then the subclass rule."""
        from paddle_tpu.core.sparse_grad import RowSparseGrad
        use_master = self._multi_precision and pv.dtype in (
            jnp.bfloat16, jnp.float16)
        if use_master:
            master = state.get("_master")
            if master is None:
                master = pv.astype(jnp.float32)
            work_p = master
        else:
            work_p = pv
        inner = {k: v for k, v in state.items() if k != "_master"}
        if isinstance(gv, RowSparseGrad):
            # weight decay is applied lazily inside the sparse rule
            new_p, new_inner = self._update_sparse(work_p, gv, inner, lr,
                                                   step)
        else:
            if not isinstance(self, _DecoupledWD):
                gv = self._apply_weight_decay(work_p, gv)
            new_p, new_inner = self._update(work_p, gv, inner, lr, step)
        if use_master:
            new_inner = dict(new_inner)
            new_inner["_master"] = new_p
        return new_p, new_inner

    @no_grad()
    def _noop(self):
        pass

    def clear_grad(self, set_to_zero=False):
        if self._parameters is not None:
            for p in self._parameters:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- functional path -----------------------------------------------------
    def init_state_pytree(self, params):
        """params: pytree of raw arrays → matching pytree of state dicts."""
        return jax.tree.map(lambda p: self._init_state_full(p), params,
                            is_leaf=lambda x: isinstance(x, (jnp.ndarray,
                                                             jax.Array,
                                                             np.ndarray)))

    def apply_gradients(self, params, grads, opt_state, step,
                        lr=None, skip_clip=False):
        """Pure functional update over pytrees (jit/pjit-safe).

        params/grads: matching pytrees of arrays; opt_state from
        init_state_pytree; step: int array/scalar.  Returns
        (new_params, new_opt_state)."""
        lr = self.get_lr() if lr is None else lr
        if self._grad_clip is not None and not skip_clip:
            grads = self._grad_clip.apply_pytree(grads)

        is_arr = lambda x: isinstance(x, (jnp.ndarray, jax.Array, np.ndarray))
        flat_pk, treedef = jax.tree_util.tree_flatten_with_path(
            params, is_leaf=is_arr)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(opt_state)
        new_p, new_s = [], []
        for (path, p), g, s in zip(flat_pk, flat_g, flat_s):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            self._current_param_name = jax.tree_util.keystr(path)
            g = g.astype(jnp.float32) if self._multi_precision else g
            np_, ns = self._update_with_master(p, g, s, lr, step)
            new_p.append(np_.astype(p.dtype))
            new_s.append(ns)
        return jax.tree.unflatten(treedef, new_p), \
            jax.tree.unflatten(treedef, new_s)

    # -- state dict ----------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        if self._lr_scheduler is not None:
            out["LR_Scheduler"] = self._lr_scheduler.state_dict()
        accum = {}
        if self._parameters is not None:
            for i, p in enumerate(self._parameters):
                st = self._accumulators.get(id(p))
                if st is not None:
                    accum[p.name or f"param_{i}"] = jax.tree.map(np.asarray, st)
        out["accumulators"] = accum
        return out

    def set_state_dict(self, state):
        self._global_step = state.get("global_step", 0)
        if self._lr_scheduler is not None and "LR_Scheduler" in state:
            self._lr_scheduler.set_state_dict(state["LR_Scheduler"])
        accum = state.get("accumulators", {})
        if self._parameters is not None:
            for i, p in enumerate(self._parameters):
                key = p.name or f"param_{i}"
                if key in accum:
                    self._accumulators[id(p)] = jax.tree.map(
                        jnp.asarray, accum[key])


class _DecoupledWD:
    """Marker mixin: optimizer applies decoupled weight decay itself
    (AdamW/Lamb) instead of the L2-into-grad default."""
