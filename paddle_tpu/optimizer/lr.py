"""LR schedulers (parity: python/paddle/optimizer/lr.py — ~20 schedulers).

Host-side scalar schedules (same as the reference): `scheduler()` returns the
current lr; `.step()` advances.  For fully-jitted training loops use
`.lr_at(step)` — a pure function of the step count usable inside jit."""

from __future__ import annotations

import math

__all__ = ["LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
           "InverseTimeDecay", "PolynomialDecay", "LinearWarmup",
           "PiecewiseDecay", "CosineAnnealingDecay", "StepDecay",
           "MultiStepDecay", "LambdaDecay", "ReduceOnPlateau",
           "MultiplicativeDecay", "OneCycleLR", "CyclicLR",
           "CosineAnnealingWarmRestarts"]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def get_lr(self):
        raise NotImplementedError

    def lr_at(self, step):
        """Pure schedule for jitted loops; defaults to host formula."""
        saved = self.last_epoch
        self.last_epoch = int(step)
        try:
            return self.get_lr()
        finally:
            self.last_epoch = saved

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]
        self.last_lr = state["last_lr"]

    set_dict = set_state_dict
    state_keys = state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** max(self.last_epoch, 0)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * max(self.last_epoch, 0))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * max(self.last_epoch, 0))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if self.cycle:
            div = math.ceil(step / self.decay_steps) or 1
            decay = self.decay_steps * div
        else:
            decay = self.decay_steps
            step = min(step, decay)
        frac = (1 - step / decay) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate,
                                                    LRScheduler) else None
        self.target = learning_rate if not self.lr_sched else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if step < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * \
                step / self.warmup_steps
        if self.lr_sched is not None:
            self.lr_sched.last_epoch = step - self.warmup_steps
            return self.lr_sched.get_lr()
        return float(self.target)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        for b, v in zip(self.boundaries, self.values):
            if step < b:
                return v
        return self.values[len(self.boundaries)]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * step / self.T_max)) / 2


class CosineAnnealingWarmRestarts(LRScheduler):
    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0,
                 last_epoch=-1, verbose=False):
        self.T_0, self.T_mult, self.eta_min = T_0, T_mult, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        t_i = self.T_0
        t_cur = step
        while t_cur >= t_i:
            t_cur -= t_i
            t_i *= self.T_mult
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * t_cur / t_i)) / 2


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (max(self.last_epoch, 0) //
                                             self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        n = sum(1 for m in self.milestones if step >= m)
        return self.base_lr * self.gamma ** n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(max(self.last_epoch, 0))


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._lr = float(learning_rate)
        self.base_lr = float(learning_rate)
        self.last_epoch = 0
        self.last_lr = self._lr
        self.verbose = verbose

    def get_lr(self):
        return self._lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        import numpy as np
        value = float(np.asarray(metrics).reshape(-1)[0])
        if self.best is None:
            self.best = value
        else:
            better = value < self.best - (abs(self.best) * self.threshold
                                          if self.threshold_mode == "rel"
                                          else self.threshold) \
                if self.mode == "min" else \
                value > self.best + (abs(self.best) * self.threshold
                                     if self.threshold_mode == "rel"
                                     else self.threshold)
            if better:
                self.best = value
                self.num_bad = 0
            else:
                self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            self._lr = max(self._lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        self.last_lr = self._lr


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3,
                 anneal_strategy="cos", three_phase=False, last_epoch=-1,
                 verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _anneal(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return start + (end - start) * pct

    def get_lr(self):
        step = max(self.last_epoch, 0)
        up = self.phase_pct * self.total_steps
        if step <= up:
            return self._anneal(self.initial_lr, self.max_lr,
                                step / max(up, 1))
        pct = (step - up) / max(self.total_steps - up, 1)
        return self._anneal(self.max_lr, self.end_lr, min(pct, 1.0))


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate,
                 step_size_up=2000, step_size_down=None, mode="triangular",
                 exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.base_lr_ = base_learning_rate
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        cycle_len = self.up + self.down
        cycle = step // cycle_len
        x = step - cycle * cycle_len
        if x < self.up:
            pct = x / self.up
        else:
            pct = 1 - (x - self.up) / self.down
        amp = self.max_lr - self.base_lr_
        if self.mode == "triangular2":
            amp = amp / (2 ** cycle)
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** step)
        return self.base_lr_ + amp * pct
