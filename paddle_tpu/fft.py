"""paddle.fft parity — the spectral API surface.

Reference: python/paddle/fft.py (fft_c2c/c2r/r2c ops over cuFFT/onemkl).
TPU-native: every transform is a generated schema op (ops/gen/ops.yaml →
ops/generated_math.py) lowering to jnp.fft — XLA's FFT emitter supplies
the kernel; numpy oracles test each one in the OpTest harness.
"""

from __future__ import annotations

from paddle_tpu.ops.generated_math import (  # noqa: F401
    fft, fft2, fftfreq, fftn, fftshift, hfft, ifft, ifft2, ifftn,
    ifftshift, ihfft, irfft, irfft2, irfftn, rfft, rfft2, rfftfreq, rfftn)

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft",
           "irfft", "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
           "fftshift", "ifftshift", "fftfreq", "rfftfreq"]
