"""paddle_tpu.io — Dataset/DataLoader (reference: python/paddle/io/)."""

from paddle_tpu.io.dataset import (  # noqa: F401
    BatchSampler, ChainDataset, ConcatDataset, Dataset,
    DistributedBatchSampler, IterableDataset, RandomSampler, Sampler,
    SequenceSampler, Subset, TensorDataset, WeightedRandomSampler,
    random_split)
from paddle_tpu.io.dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, get_worker_info)
from paddle_tpu.io.device_prefetch import (  # noqa: F401
    DevicePrefetchIterator, device_prefetch)

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ConcatDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "default_collate_fn",
    "get_worker_info", "DevicePrefetchIterator", "device_prefetch",
]
