"""TokenFileDataset — native high-throughput LM data feed (csrc/datafeed).

Reference parity: the C++ data pipeline behind paddle.io.DataLoader and PS
training (paddle/fluid/framework/data_feed.cc, buffered_reader.cc,
operators/reader/): multi-threaded native workers assembling batches into a
bounded queue the trainer drains.

Usage: a corpus pre-tokenized to a flat binary int32 file; yields
{"input_ids": [B, S], "labels": [B, S]} numpy batches (labels = shifted
window) with worker threads + double buffering in C++.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator

import numpy as np

from paddle_tpu.io.dataset import IterableDataset

__all__ = ["TokenFileDataset", "write_token_file"]


def write_token_file(path: str, tokens) -> str:
    """Helper: dump an int sequence to the flat int32 format."""
    arr = np.asarray(tokens, np.int32)
    arr.tofile(path)
    return path


def _lib():
    from paddle_tpu.utils.cpp_extension import load_native
    lib = load_native("datafeed")
    lib.datafeed_open.restype = ctypes.c_void_p
    lib.datafeed_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.datafeed_num_batches.restype = ctypes.c_int64
    lib.datafeed_num_batches.argtypes = [ctypes.c_void_p]
    lib.datafeed_num_tokens.restype = ctypes.c_int64
    lib.datafeed_num_tokens.argtypes = [ctypes.c_void_p]
    lib.datafeed_next.restype = ctypes.c_int
    lib.datafeed_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.datafeed_close.argtypes = [ctypes.c_void_p]
    return lib


class TokenFileDataset(IterableDataset):
    def __init__(self, path: str, seq_len: int, batch_size: int,
                 shuffle: bool = True, seed: int = 0, num_threads: int = 2,
                 queue_depth: int = 4, epochs: int = 1):
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_threads = num_threads
        self.queue_depth = queue_depth
        self.epochs = epochs
        self._lib = _lib()
        self._handle = self._lib.datafeed_open(
            path.encode(), seq_len, batch_size, int(shuffle), seed,
            num_threads, queue_depth)
        if not self._handle:
            raise ValueError(
                f"datafeed_open failed for {path} (too small for "
                f"seq_len={seq_len}, batch_size={batch_size}?)")

    @property
    def num_batches(self) -> int:
        return int(self._lib.datafeed_num_batches(self._handle))

    @property
    def num_tokens(self) -> int:
        return int(self._lib.datafeed_num_tokens(self._handle))

    def __iter__(self) -> Iterator[dict]:
        buf = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        epoch = 0
        while epoch < self.epochs:
            rc = self._lib.datafeed_next(
                self._handle, buf.ctypes.data_as(ctypes.c_void_p))
            if rc < 0:
                raise RuntimeError("datafeed_next failed")
            yield {"input_ids": buf[:, :-1].copy(),
                   "labels": buf[:, 1:].copy()}
            if rc == 1:
                epoch += 1

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.datafeed_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
