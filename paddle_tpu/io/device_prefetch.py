"""Device-prefetch iterator — H2D transfer overlapped one batch ahead.

The host-side ``DataLoader`` already overlaps batch ASSEMBLY with the
step, but the ``jax.device_put`` (host→device DMA) still ran inline in
the training loop: with a synchronous dispatch gap it serializes with
the compiled step.  This iterator keeps a background thread one (or
``depth``) batches ahead, so by the time the loop asks for batch N+1 its
arrays are already device-resident — the double-buffering the reference
gets from ``create_py_reader`` + the C++ blocking queue, done with one
thread and XLA's transfer engine.

Sharded placement: pass ``sharding=`` (a ``jax.sharding.Sharding``
applied to every array leaf) or ``mesh=`` + ``spec=`` and each batch
lands pre-sharded (the same placement ``TrainStep(batch_spec=...)``
would do inline, minus the step-blocking transfer).

Telemetry: ``paddle_tpu_prefetch_depth`` (pull gauge, current buffered
batches), ``paddle_tpu_prefetch_batches_total``.

Usage::

    for batch in device_prefetch(loader, depth=2):
        loss = step(batch)

or explicitly close on early exit::

    it = device_prefetch(gen())
    with it:
        for batch in it: ...
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

__all__ = ["DevicePrefetchIterator", "device_prefetch"]


def _prefetch_metrics():
    from paddle_tpu.observability import default_registry
    reg = default_registry()
    return {
        "depth": reg.gauge(
            "paddle_tpu_prefetch_depth",
            "device-resident batches currently buffered ahead of the "
            "training loop"),
        "batches": reg.counter(
            "paddle_tpu_prefetch_batches_total",
            "batches moved host→device by the prefetch thread"),
    }


class DevicePrefetchIterator:
    """Iterates ``src``, placing every batch on device from a background
    thread ``depth`` batches ahead of the consumer."""

    _STOP = object()

    def __init__(self, src: Iterable, depth: int = 2, sharding=None,
                 mesh=None, spec=None, device=None):
        import jax

        if sharding is None and mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sharding = NamedSharding(mesh, spec if spec is not None
                                     else PartitionSpec())
        self._sharding = sharding
        self._device = device
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._done = False
        self._metrics = _prefetch_metrics()
        self._metrics["depth"].set_function(self._q.qsize)
        # explicit context propagation: capture the constructing
        # thread's span context so transfers traced on the background
        # thread stay part of the caller's trace
        from paddle_tpu.observability.tracing import tracer
        self._tracer = tracer()
        self._ctx = self._tracer.current_context()

        def place(batch) -> Any:
            if self._sharding is not None:
                return jax.device_put(batch, self._sharding)
            if self._device is not None:
                return jax.device_put(batch, self._device)
            return jax.device_put(batch)

        def worker():
            it = iter(src)
            try:
                with self._tracer.attach(self._ctx):
                    self._worker_loop(it, place)
            except BaseException as e:  # propagate to consumer
                self._exc = e
            finally:
                if hasattr(it, "close"):
                    try:
                        it.close()
                    except Exception:
                        pass
                # the sentinel must not be dropped on a full queue (the
                # consumer would block forever); only give up once the
                # consumer has explicitly closed
                while True:
                    try:
                        self._q.put(self._STOP, timeout=0.05)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="paddle_tpu-device-prefetch")
        self._thread.start()

    def _worker_loop(self, it, place):
        for item in it:
            if self._stop.is_set():
                break
            with self._tracer.span("prefetch.place",
                                   root_eligible=False):
                dev = place(item)
            self._metrics["batches"].inc()
            while not self._stop.is_set():
                try:
                    self._q.put(dev, timeout=0.05)
                    break
                except queue.Full:
                    continue
            else:
                break

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if item is self._STOP:
            self._done = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def close(self):
        """Stop the prefetch thread and drop buffered batches.  Safe to
        call more than once; also runs on GC and context-manager exit so
        a consumer that stops iterating early leaks nothing."""
        self._stop.set()
        while True:  # unblock a worker stuck on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        self._done = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(src: Iterable, depth: int = 2, sharding=None,
                    mesh=None, spec=None, device=None) -> \
        DevicePrefetchIterator:
    """Wrap any batch iterable so host→device transfer happens ``depth``
    batches ahead on a background thread (sharded placement when ``mesh``
    — or an explicit ``sharding`` — is given)."""
    return DevicePrefetchIterator(src, depth=depth, sharding=sharding,
                                  mesh=mesh, spec=spec, device=device)
