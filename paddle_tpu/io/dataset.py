"""Datasets + samplers.

Reference parity: ``paddle.io`` — Dataset/IterableDataset
(python/paddle/io/dataloader/dataset.py), Sampler/BatchSampler/
DistributedBatchSampler (batch_sampler.py, sampler.py).
"""

from __future__ import annotations

import bisect
from typing import Iterable, List, Optional, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ConcatDataset",
           "ChainDataset", "Subset", "random_split", "Sampler",
           "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        lens = {len(t) for t in tensors}
        if len(lens) != 1:
            raise ValueError("tensors must share dim 0")
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds - 1] if ds else 0
        return self.datasets[ds][idx - prev]

    def __len__(self):
        return self.cumulative_sizes[-1]


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("lengths must sum to dataset size")
    rng = generator or np.random.default_rng()
    perm = rng.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = self.generator
        if rng is None:
            # seeded-framework determinism: a paddle_tpu.seed(s) run
            # must shuffle reproducibly (and still differently per
            # epoch) — OS entropy here made every fit() non-repeatable
            from paddle_tpu.core.state import derive_seed
            rng = np.random.default_rng(derive_seed())
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.default_rng().choice(
            len(p), self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle \
                else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across ranks (reference
    io/dataloader/batch_sampler.py DistributedBatchSampler).  Under
    single-controller SPMD each *host* loads 1/num_replicas of the global
    batch; with one host this degenerates to BatchSampler."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu.distributed.env import get_rank, get_world_size
        self.num_replicas = num_replicas if num_replicas is not None \
            else get_world_size()
        self.rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.epoch = 0
        super().__init__(dataset, None, False, batch_size, drop_last)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.data_source)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad so every rank gets the same count (reference behaviour)
        total = ((n + self.num_replicas - 1) // self.num_replicas
                 * self.num_replicas)
        indices += indices[:total - n]
        local = indices[self.rank::self.num_replicas]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = (len(self.data_source) + self.num_replicas - 1) \
            // self.num_replicas
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size
