"""DataLoader — host-side batching + background prefetch.

Reference parity: ``paddle.io.DataLoader`` (io/reader.py:218) — there,
multiprocess workers push batches through shared-memory queues into a C++
``LoDTensorBlockingQueue`` read by a ``create_py_reader`` op
(io/dataloader/dataloader_iter.py:201, operators/reader/).

TPU-native design: the device never blocks on input — batches are assembled
on host (optionally by a process pool), then a background thread keeps a
small prefetch queue ahead of the training loop, overlapping host work with
device steps.  jit'd steps dispatch asynchronously, so one queue + one
thread gives the same pipelining the reference's blocking-queue machinery
does, without native code (XLA's transfer engine does the H2D overlap).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Callable, Iterable, Optional

import numpy as np

from paddle_tpu.io.dataset import (BatchSampler, Dataset, IterableDataset,
                                   SequenceSampler)

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info",
           "WorkerInfo"]


def default_collate_fn(batch):
    """Stack a list of samples into numpy batch arrays (structure-aware)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch])
                for k in sample}
    if hasattr(sample, "numpy"):  # Tensor
        return np.stack([np.asarray(s.numpy()) for s in batch])
    arr = np.asarray(sample)
    if arr.dtype == object:
        return batch
    return np.stack([np.asarray(s) for s in batch])


class _PrefetchIterator:
    """Background-thread prefetch with EXPLICIT lifecycle: a consumer
    that stops iterating early (break / exception / GC) must not leave
    the thread parked on a full queue or the pool holding in-flight
    futures — ``close()`` (also fired by ``__del__`` and context exit)
    stops the worker and finalizes the underlying generator, which
    unwinds its ``finally`` blocks (future cancellation lives there)."""

    _STOP = object()

    def __init__(self, gen_fn: Callable[[], Iterable], depth: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._exc = None
        self._done = False
        self._stop = threading.Event()
        # explicit context propagation: batch-assembly spans recorded on
        # the prefetch thread stay part of the constructing trace
        from paddle_tpu.observability.tracing import tracer
        self._tracer = tracer()
        self._ctx = self._tracer.current_context()

        def worker():
            gen = gen_fn()
            it = iter(gen)
            try:
                with self._tracer.attach(self._ctx):
                    while not self._stop.is_set():
                        # batch assembly (sampling + __getitem__ +
                        # collate all run inside next()) gets its own
                        # span; the sentinel default sidesteps
                        # StopIteration-through-contextmanager
                        with self._tracer.span("dataloader.batch",
                                               root_eligible=False):
                            item = next(it, self._STOP)
                        if item is self._STOP:
                            break
                        while not self._stop.is_set():
                            try:
                                self._q.put(item, timeout=0.05)
                                break
                            except queue.Full:
                                continue
                        else:
                            break
            except BaseException as e:  # propagate to consumer
                self._exc = e
            finally:
                if hasattr(gen, "close"):
                    try:
                        gen.close()   # runs the generator's finally blocks
                    except Exception:
                        pass
                # the sentinel must not be dropped on a full queue (the
                # consumer would block forever); only give up once the
                # consumer has explicitly closed
                while True:
                    try:
                        self._q.put(self._STOP, timeout=0.05)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="paddle_tpu-dataloader-prefetch")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if getattr(self, "_done", False):
            raise StopIteration  # the single _STOP sentinel was consumed
        item = self._q.get()
        if item is self._STOP:
            self._done = True
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def close(self):
        """Stop the prefetch thread, finalize the source generator, and
        drop buffered batches.  Idempotent."""
        self._stop.set()
        while True:  # unblock a worker stuck on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
        self._done = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler=None,
                 batch_size: Optional[int] = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Callable = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: int = 0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(1, prefetch_factor)
        self.use_buffer_reader = use_buffer_reader
        # per-batch result deadline (seconds; 0 = wait forever, the
        # reference's semantics): a worker stuck in __getitem__ becomes a
        # clear RuntimeError instead of an indefinite consumer hang
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)

        if self._iterable_mode:
            if batch_sampler is not None:
                raise ValueError("batch_sampler invalid for IterableDataset")
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        else:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last) if batch_size is not None else None

        self._pool = None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # -- batch generation ----------------------------------------------------
    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _result(self, fut):
        """One pool future → batch, with worker death surfaced as a
        clear RuntimeError naming the dead worker processes — a crashed
        worker (OOM-killed, segfaulted C extension, os._exit) otherwise
        reads as either an opaque BrokenProcessPool or, in naive queue
        designs, an indefinite consumer hang."""
        import concurrent.futures as cf
        from concurrent.futures.process import BrokenProcessPool
        alive_before = self._worker_pids()
        try:
            return fut.result(timeout=self.timeout or None)
        except cf.TimeoutError:
            raise RuntimeError(
                f"DataLoader batch not produced within timeout="
                f"{self.timeout}s (worker pids {sorted(alive_before)}) — "
                "a worker is stuck in dataset.__getitem__/collate_fn")
        except BrokenProcessPool as e:
            dead = self._dead_workers()
            self._pool = None  # broken pools cannot be reused
            who = f"worker pid(s) {dead}" if dead else \
                f"one of worker pids {sorted(alive_before)}"
            raise RuntimeError(
                f"DataLoader worker process died: {who} terminated "
                f"abruptly (num_workers={self.num_workers}); look for "
                "OOM kills or native crashes in dataset code") from e

    def _worker_pids(self):
        pool = self._pool
        try:
            return set(pool._processes or {}) if pool is not None else set()
        except Exception:
            return set()

    def _dead_workers(self):
        pool = self._pool
        try:
            return sorted(pid for pid, p in (pool._processes or {}).items()
                          if not p.is_alive())
        except Exception:
            return []

    def _submit(self, indices):
        """Submit one index batch, translating a broken pool the same
        way ``_result`` does — a worker that died between batches breaks
        the pool before any future exists, and the raw
        ``BrokenProcessPool`` from ``submit`` named nobody."""
        from concurrent.futures.process import BrokenProcessPool
        try:
            return self._pool.submit(_fetch_worker, self.dataset,
                                     self.collate_fn, indices)
        except BrokenProcessPool as e:
            dead = self._dead_workers()
            self._pool = None  # broken pools cannot be reused
            who = f"worker pid(s) {dead}" if dead else "a worker"
            raise RuntimeError(
                f"DataLoader worker process died: {who} terminated "
                f"abruptly (num_workers={self.num_workers}); look for "
                "OOM kills or native crashes in dataset code") from e

    def _gen_map_style(self):
        if self.num_workers > 0 and self.batch_sampler is not None:
            # process pool maps index batches; order preserved
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor
            if self._pool is None:
                counter = multiprocessing.Value("i", 0)
                base_seed = int(np.random.default_rng().integers(2 ** 31))
                self._pool = ProcessPoolExecutor(
                    self.num_workers, initializer=_worker_init,
                    initargs=(counter, self.num_workers, base_seed))
            inflight = self.num_workers * self.prefetch_factor
            it = iter(self.batch_sampler)
            import collections
            dq = collections.deque()
            try:
                for _ in range(inflight):
                    try:
                        dq.append(self._submit(next(it)))
                    except StopIteration:
                        break
                while dq:
                    fut = dq.popleft()
                    yield self._result(fut)
                    try:
                        dq.append(self._submit(next(it)))
                    except StopIteration:
                        pass
            finally:
                # generator finalized early (consumer broke out): drop
                # queued work so the pool drains instead of grinding
                # through the whole epoch
                for fut in dq:
                    fut.cancel()
        else:
            if self.batch_sampler is None:
                for i in range(len(self.dataset)):
                    yield self.dataset[i]
            else:
                for indices in self.batch_sampler:
                    yield self._fetch(indices)

    def _gen_iterable(self):
        if self.batch_size is None:
            yield from self.dataset
            return
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        gen = self._gen_iterable if self._iterable_mode \
            else self._gen_map_style
        if self.use_buffer_reader:
            return _PrefetchIterator(gen, depth=self.prefetch_factor)
        return iter(gen())

    def close(self):
        """Shut down the worker pool.  Live ``_PrefetchIterator``s hold
        their own ``close()``; call both when tearing down mid-epoch."""
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None

    def __del__(self):
        self.close()


from collections import namedtuple

WorkerInfo = namedtuple("WorkerInfo", ["id", "num_workers", "seed",
                                       "dataset"])
_worker_info = None


def get_worker_info():
    """Inside a map-style DataLoader WORKER PROCESS: that worker's
    stable info (id assigned once per process, seed = base_seed + id);
    in the main process: None (reference io/dataloader/worker.py:81).
    Iterable datasets iterate in the main process here, so sharding by
    worker id is a map-style concern only."""
    return _worker_info


def _worker_init(counter, num_workers, base_seed):
    """Pool initializer: runs ONCE per worker process — the id is the
    process's identity, not a per-task round-robin (a dataset keying
    per-worker resources or RNG on it needs it stable)."""
    global _worker_info
    with counter.get_lock():
        wid = counter.value
        counter.value += 1
    _worker_info = WorkerInfo(id=wid, num_workers=num_workers,
                              seed=base_seed + wid, dataset=None)


def _fetch_worker(dataset, collate_fn, indices):
    # chaos hook: runs IN the worker process (the registry re-reads
    # PADDLE_TPU_FAULTS there), so action=exit is a genuine hard worker
    # death and the default raise travels back through fut.result()
    from paddle_tpu.robustness import fault_point
    fault_point("io.dataloader.worker", pid=os.getpid())
    return collate_fn([dataset[i] for i in indices])
