"""paddle.incubate parity namespace."""
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import autograd  # noqa: F401
