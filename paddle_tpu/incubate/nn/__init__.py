"""paddle.incubate.nn parity: fused transformer building blocks.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention :272, FusedFeedForward :559,
FusedTransformerEncoderLayer), fused_linear.py, fused_dropout_add.py.
On TPU the fusion is the compiler's: these layers express the whole
block as one traceable region (attention routes to the Pallas flash
kernel when shapes allow).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.incubate.nn import functional as FF

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear",
           "FusedDropoutAdd"]


class FusedLinear(Layer):
    """reference incubate/nn/layer/fused_linear.py."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape)
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_features], is_bias=True)

    def forward(self, x):
        return FF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self.transpose_weight)


class FusedDropoutAdd(Layer):
    """reference incubate/nn/layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return FF.fused_dropout_add(x, y, p=self.p,
                                    training=self.training, mode=self.mode)


class FusedMultiHeadAttention(Layer):
    """reference fused_transformer.py:272 — pre/post-LN + fused QKV +
    attention + out-proj + dropout + residual in one layer."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("num_heads must divide embed_dim")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        h, hd, e = num_heads, self.head_dim, embed_dim
        self.qkv_weight = self.create_parameter([3, h, hd, e])
        self.qkv_bias = None if qkv_bias_attr is False else \
            self.create_parameter([3, h, hd], is_bias=True)
        self.linear_weight = self.create_parameter([e, e])
        self.linear_bias = None if linear_bias_attr is False else \
            self.create_parameter([e], is_bias=True)
        self.pre_ln_scale = self.create_parameter([e], is_bias=False)
        self.pre_ln_scale.set_value(np.ones(e, np.float32))
        self.pre_ln_bias = self.create_parameter([e], is_bias=True)
        self.ln_scale = self.create_parameter([e], is_bias=False)
        self.ln_scale.set_value(np.ones(e, np.float32))
        self.ln_bias = self.create_parameter([e], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return FF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            num_heads=self.num_heads, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate, attn_mask=attn_mask,
            training=self.training)


class FusedFeedForward(Layer):
    """reference fused_transformer.py:559."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward])
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model])
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter([d_model])
        self.ln1_scale.set_value(np.ones(d_model, np.float32))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter([d_model])
        self.ln2_scale.set_value(np.ones(d_model, np.float32))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, x):
        return FF.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """reference fused_transformer.py FusedTransformerEncoderLayer:
    FusedMultiHeadAttention + FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate if attn_dropout_rate is None
            else attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
