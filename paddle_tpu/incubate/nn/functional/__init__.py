"""Fused functional ops (parity: python/paddle/incubate/nn/functional/).

TPU-native: "fused" means expressed as one jit-traceable expression XLA
fuses (elementwise epilogues fold into the matmul) or routed to the Pallas
flash-attention kernel — the reference's hand-written fused CUDA kernels
(fused_multi_transformer_op.cu, fused_gemm_epilogue) become compiler work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op

__all__ = ["fused_matmul_bias", "fused_linear",
           "fused_multi_head_attention", "fused_feedforward",
           "fused_dropout_add", "memory_efficient_attention"]


@eager_op
def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    """matmul + bias epilogue (reference fused_gemm_epilogue kernel)."""
    out = jnp.matmul(jnp.swapaxes(x, -1, -2) if transpose_x else x,
                     jnp.swapaxes(y, -1, -2) if transpose_y else y)
    return out if bias is None else out + bias


def fused_linear(x, weight, bias=None, transpose_weight=False):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


@eager_op
def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train"):
    """dropout(x) + y in one fused expression
    (reference incubate/nn/layer/fused_dropout_add.py)."""
    if not training or p == 0.0:
        # downscale_in_infer scales at inference (reference F.dropout
        # semantics); upscale_in_train is identity here
        if mode == "downscale_in_infer" and p > 0.0:
            return x * (1.0 - p) + y
        return x + y
    from paddle_tpu.core import state as _cs
    keep = jax.random.bernoulli(_cs.next_key(), 1.0 - p, jnp.shape(x))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0) + y
    return jnp.where(keep, x, 0.0) + y


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True):
    """reference incubate/nn/memory_efficient_attention.py: O(s) memory
    attention — on TPU this IS the flash/sdpa path (online softmax in the
    Pallas kernel; XLA-fused reference math otherwise).
    q/k/v: [batch, seq, heads, head_dim]."""
    from paddle_tpu.nn.functional.attention import scaled_dot_product_attention
    return scaled_dot_product_attention(
        query, key, value, attn_mask=attn_bias,
        dropout_p=p if training else 0.0, is_causal=False, scale=scale)


@eager_op
def fused_multi_head_attention(x, qkv_weight, linear_weight, *,
                               qkv_bias=None, linear_bias=None,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None,
                               pre_layer_norm=False, epsilon=1e-5,
                               num_heads=None,
                               dropout_rate=0.0, attn_dropout_rate=0.0,
                               attn_mask=None, training=True):
    """One-call transformer attention block (reference
    incubate/nn/functional/fused_transformer.py fused_multi_head_attention):
    [pre-LN] -> fused QKV -> SDPA -> out proj -> dropout -> residual
    [-> post-LN].  qkv_weight: [3, heads, head_dim, embed]."""
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.nn import functional as F

    xr = x
    qkv_w = qkv_weight
    three, h, hd, e = qkv_w.shape
    assert three == 3
    residual = xr
    if pre_layer_norm:
        xr = unwrap(F.layer_norm(xr, [e], pre_ln_scale, pre_ln_bias,
                                 epsilon))
    qkv = jnp.einsum("bse,thde->bsthd", xr, qkv_w)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias[None, None]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [b,s,h,hd]
    out = unwrap(F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0))
    # linear_weight: [embed, embed] viewed as [heads, head_dim, embed]
    out = jnp.einsum("bshd,hde->bse", out, linear_weight.reshape(h, hd, e))
    if linear_bias is not None:
        out = out + linear_bias
    if dropout_rate and training:
        from paddle_tpu.core import state as _cs
        keep = jax.random.bernoulli(_cs.next_key(), 1.0 - dropout_rate,
                                    out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
    out = residual + out
    if not pre_layer_norm:
        out = unwrap(F.layer_norm(out, [e], ln_scale, ln_bias, epsilon))
    return out


@eager_op
def fused_feedforward(x, linear1_weight, linear2_weight, *,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None,
                      dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", pre_layer_norm=False,
                      epsilon=1e-5, training=True):
    """reference fused_feedforward: [pre-LN] -> linear -> act -> dropout ->
    linear -> dropout -> residual [-> post-LN]."""
    from paddle_tpu.core.dispatch import unwrap
    from paddle_tpu.nn import functional as F
    from paddle_tpu.core import state as _cs

    xr = x
    e = xr.shape[-1]
    residual = xr
    if pre_layer_norm:
        xr = unwrap(F.layer_norm(xr, [e], ln1_scale, ln1_bias, epsilon))
    h = jnp.matmul(xr, linear1_weight)
    if linear1_bias is not None:
        h = h + linear1_bias
    act = getattr(F, activation)
    h = unwrap(act(h))
    if dropout1_rate and training:
        keep = jax.random.bernoulli(_cs.next_key(), 1.0 - dropout1_rate,
                                    h.shape)
        h = jnp.where(keep, h / (1.0 - dropout1_rate), 0.0)
    out = jnp.matmul(h, linear2_weight)
    if linear2_bias is not None:
        out = out + linear2_bias
    if dropout2_rate and training:
        keep = jax.random.bernoulli(_cs.next_key(), 1.0 - dropout2_rate,
                                    out.shape)
        out = jnp.where(keep, out / (1.0 - dropout2_rate), 0.0)
    out = residual + out
    if not pre_layer_norm:
        out = unwrap(F.layer_norm(out, [e], ln2_scale, ln2_bias, epsilon))
    return out
