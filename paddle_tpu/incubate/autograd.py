"""Functional autodiff transforms (parity: python/paddle/incubate/autograd):
jvp/vjp/jacobian/hessian/vhp over pure functions — thin veneers on jax's
transforms, unwrapping/wrapping eager Tensors at the boundary."""

from __future__ import annotations

import jax

from paddle_tpu.core.dispatch import unwrap, wrap_like

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "jacobian", "hessian",
           "vhp"]


def _uw(tree):
    return jax.tree.map(unwrap, tree,
                        is_leaf=lambda t: hasattr(t, "_data"))


def _w(tree):
    return jax.tree.map(wrap_like, tree)


def _fn_raw(func):
    def f(*args):
        return _uw(func(*args))
    return f


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    v = v if v is None or isinstance(v, (tuple, list)) else (v,)
    primals = tuple(_uw(x) for x in xs)
    tangents = tuple(_uw(t) for t in v) if v is not None else \
        tuple(jax.numpy.ones_like(p) for p in primals)
    out, jv = jax.jvp(_fn_raw(func), primals, tangents)
    return _w(out), _w(jv)


def vjp(func, xs, v=None):
    xs = xs if isinstance(xs, (tuple, list)) else (xs,)
    primals = tuple(_uw(x) for x in xs)
    out, pull = jax.vjp(_fn_raw(func), *primals)
    if v is None:
        v = jax.tree.map(jax.numpy.ones_like, out)
    else:
        v = _uw(v)
    grads = pull(v)
    return _w(out), _w(grads)


def jacobian(func, xs):
    xs_t = xs if isinstance(xs, (tuple, list)) else (xs,)
    primals = tuple(_uw(x) for x in xs_t)
    jac = jax.jacrev(_fn_raw(func), argnums=tuple(range(len(primals))))(
        *primals)
    out = _w(jac)
    return out if isinstance(xs, (tuple, list)) else out[0]


Jacobian = jacobian


def hessian(func, xs):
    xs_t = xs if isinstance(xs, (tuple, list)) else (xs,)
    primals = tuple(_uw(x) for x in xs_t)
    hes = jax.hessian(_fn_raw(func), argnums=tuple(range(len(primals))))(
        *primals)
    out = _w(hes)
    return out if isinstance(xs, (tuple, list)) else out[0][0]


Hessian = hessian


def vhp(func, xs, v=None):
    """vector-Hessian product: v^T H of a scalar func."""
    xs_t = xs if isinstance(xs, (tuple, list)) else (xs,)
    primals = tuple(_uw(x) for x in xs_t)
    vg = jax.value_and_grad(_fn_raw(func),
                            argnums=tuple(range(len(primals))))
    if v is None:
        v = tuple(jax.numpy.ones_like(p) for p in primals)
    else:
        v = tuple(_uw(t) for t in (v if isinstance(v, (tuple, list))
                                   else (v,)))
    (out, _), (_, vhp_val) = jax.jvp(vg, primals, v)
    return _w(out), _w(vhp_val)
