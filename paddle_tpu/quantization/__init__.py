"""paddle_tpu.quantization — PTQ / QAT / quantized serving.

Reference parity: ``paddle.quantization`` (python/paddle/quantization/:
QuantConfig + PTQ/QAT entries (quantize.py, ptq.py, qat.py), observers
(observers/abs_max.py …), quanters (quanters/act_lsq.py …)).

Two halves:

* **Calibration-time** (this module) — observers (abs-max, moving
  average, histogram, KL/entropy), fake-quant with STE gradients
  (``FakeQuantLinear``), and the ``PTQ``/``QAT`` calibrate→convert
  drivers, all producing :class:`QuantedLinear` inference layers.
* **Serving-time** (``quantization.serving``) — the TPU subsystem
  behind ``PADDLE_TPU_QUANT_WEIGHTS=int8|fp8`` and
  ``PADDLE_TPU_QUANT_KV=int8``: :func:`quantize_for_serving` converts
  a model's large Linears to weight-only :class:`QuantedLinear`
  (int8 or ``float8_e4m3fn`` at rest, per-output-channel fp32 scales)
  whose matmuls run the Pallas quant kernel
  (``ops/pallas/quant_matmul.py`` — dequant fused into the fp32 MXU
  accumulator, tile sizes one more autotune axis); the serving engine
  adopts the conversion at construction and
  :func:`restore_from_serving` undoes it.  Quantized paged-KV block
  pools live in ``inference/kv_cache.py``; the accuracy-parity gate
  (:func:`quantization.serving.parity_report` + ``bench_serve
  --check-equivalence``) bounds logit error and greedy token drift vs
  the bf16 engine so quantization can never silently rot quality.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.core.dispatch import eager_op, unwrap, wrap_like

__all__ = ["AbsMaxObserver", "MovingAverageAbsMaxObserver",
           "HistogramObserver", "KLObserver", "QuantConfig",
           "PTQ", "QAT", "FakeQuantLinear", "QuantedLinear",
           "quant_dequant", "quantize_weight", "quantize_for_serving",
           "restore_from_serving", "quant_weights_mode"]


# -- quant math --------------------------------------------------------------

def _absmax_scale(x, bits: int = 8):
    qmax = 2.0 ** (bits - 1) - 1
    return jnp.maximum(jnp.max(jnp.abs(x)), 1e-8) / qmax


@eager_op
def quant_dequant(x, scale, bits: int = 8):
    """Symmetric fake-quant with straight-through gradient."""
    qmax = 2.0 ** (bits - 1) - 1

    @jax.custom_vjp
    def _qdq(v, s):
        q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax)
        return q * s

    def _fwd(v, s):
        return _qdq(v, s), (v, s)

    def _bwd(res, g):
        v, s = res
        # STE: pass gradient through where un-clipped
        mask = (jnp.abs(v / s) <= qmax + 1).astype(g.dtype)
        return g * mask, jnp.zeros_like(s)

    _qdq.defvjp(_fwd, _bwd)
    return _qdq(x, scale)


def quantize_weight(w, bits: int = 8, axis: Optional[int] = None):
    """Real quantization: returns (int8 values, fp scale).  Per-channel if
    `axis` given (the out-features axis for linear weights)."""
    w = unwrap(w)
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        scale = _absmax_scale(w, bits)
    else:
        red = tuple(i for i in range(w.ndim) if i != axis)
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=red, keepdims=True),
                            1e-8) / qmax
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale


# -- observers ---------------------------------------------------------------

class AbsMaxObserver:
    """reference observers/abs_max.py: running max(|x|) → scale."""

    def __init__(self, quant_bits: int = 8):
        self.bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        arr = unwrap(x)
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(arr))))

    __call__ = observe

    def scale(self):
        qmax = 2.0 ** (self.bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class HistogramObserver(AbsMaxObserver):
    """reference observers/hist.py: accumulate an |x| histogram over
    calibration batches; scale from the `percent` quantile of mass."""

    def __init__(self, quant_bits: int = 8, bins_count: int = 2048,
                 percent: float = 0.9999):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._hist = np.zeros(bins_count, np.float64)
        self._range = 0.0

    def observe(self, x):
        arr = np.abs(np.asarray(unwrap(x), np.float32)).ravel()
        cur_max = float(arr.max()) if arr.size else 0.0
        if cur_max > self._range:
            # re-bin the existing histogram into the wider range
            if self._range > 0.0 and self._hist.sum() > 0:
                old_edges = np.linspace(0, self._range, self.bins_count + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                new_hist, _ = np.histogram(
                    centers, bins=self.bins_count, range=(0, cur_max),
                    weights=self._hist)
                self._hist = new_hist.astype(np.float64)
            self._range = cur_max
        if self._range > 0.0 and arr.size:
            h, _ = np.histogram(arr, bins=self.bins_count,
                                range=(0, self._range))
            self._hist += h

    __call__ = observe

    def _threshold(self):
        total = self._hist.sum()
        if total == 0:
            return 1e-8
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self.percent))
        idx = min(idx, self.bins_count - 1)
        return (idx + 1) * self._range / self.bins_count

    def scale(self):
        qmax = 2.0 ** (self.bits - 1) - 1
        return max(self._threshold(), 1e-8) / qmax


class KLObserver(HistogramObserver):
    """reference observers/kl.py (TensorRT-style entropy calibration):
    pick the clip threshold minimising KL(P_clipped || Q_quantized)."""

    def __init__(self, quant_bits: int = 8, bins_count: int = 2048):
        super().__init__(quant_bits, bins_count=bins_count)

    def _threshold(self):
        total = self._hist.sum()
        if total == 0:
            return 1e-8
        levels = 2 ** (self.bits - 1)  # 128 for int8
        hist = self._hist
        best_kl, best_i = np.inf, self.bins_count
        for i in range(levels, self.bins_count + 1, 16):
            p = hist[:i].copy()
            p[i - 1] += hist[i:].sum()  # clip mass into the last bin
            p_sum = p.sum()
            if p_sum == 0:
                continue
            # quantize the first i bins down to `levels` buckets, then
            # expand back, preserving per-bucket mass over nonzero bins
            chunks = np.array_split(hist[:i], levels)
            q = np.zeros(i)
            start = 0
            for c in chunks:
                n = len(c)
                nz = c > 0
                if nz.any():
                    q[start:start + n][nz] = c[nz].sum() / nz.sum()
                start += n
            q_sum = q.sum()
            if q_sum == 0:
                continue
            pn = p / p_sum
            qn = q / q_sum
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(
                pn[mask] / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return best_i * self._range / self.bins_count


class MovingAverageAbsMaxObserver(AbsMaxObserver):
    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.rate = moving_rate
        self._initialized = False

    def observe(self, x):
        arr = unwrap(x)
        cur = float(jnp.max(jnp.abs(arr)))
        if not self._initialized:
            self._absmax = cur
            self._initialized = True
        else:
            self._absmax = self.rate * self._absmax + (1 - self.rate) * cur

    __call__ = observe


# -- config ------------------------------------------------------------------

class QuantConfig:
    """reference quantization/config.py shape: which layer types get which
    observer/quanter."""

    def __init__(self, activation=None, weight=None):
        self.activation_factory = activation or AbsMaxObserver
        self.weight_factory = weight or AbsMaxObserver
        self._layer_types = []

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        self._layer_types.extend(layer_types)

    def should_quantize(self, layer) -> bool:
        from paddle_tpu.nn.common_layers import Linear
        types = self._layer_types or [Linear]
        return isinstance(layer, tuple(types))


# -- quantized layers --------------------------------------------------------

class FakeQuantLinear(Layer):
    """QAT wrapper: fake-quant weight (and optionally activation) around the
    wrapped Linear, STE gradients (reference quanters)."""

    def __init__(self, linear, weight_bits: int = 8, act_bits: int = 8,
                 quant_act: bool = True):
        super().__init__()
        self.linear = linear
        self.weight_bits = weight_bits
        self.act_bits = act_bits
        self.quant_act = quant_act
        self.act_observer = MovingAverageAbsMaxObserver(act_bits)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        w = self.linear.weight
        w_scale = _absmax_scale(unwrap(w), self.weight_bits)
        wq = quant_dequant(w, w_scale, bits=self.weight_bits)
        if self.quant_act:
            self.act_observer.observe(x)
            xq = quant_dequant(x, jnp.asarray(self.act_observer.scale()),
                               bits=self.act_bits)
        else:
            xq = x
        return F.linear(xq, wq, self.linear.bias)


class QuantedLinear(Layer):
    """Converted inference layer: quantized weights at rest.

    Two flavours share the class:

    * **weight + activation int8** (``act_scale`` given, the PTQ/QAT
      convert target): the int8×int8→int32 GEMM shape XLA maps onto the
      MXU, output rescaled by ``x_scale * w_scale``.
    * **weight-only** (``act_scale=None`` — the serving path,
      ``quantization.serving.quantize_for_serving``): int8 or fp8
      (``float8_e4m3fn``) weights with a per-output-channel fp32 scale,
      routed through the Pallas quant matmul
      (``ops/pallas/quant_matmul.py`` — dequant fused into the fp32 MXU
      accumulator; jnp scale-multiply fallback off-TPU).
    """

    def __init__(self, linear, act_scale: Optional[float] = None,
                 bits: int = 8, mode: Optional[str] = None):
        super().__init__()
        if mode is None:
            q, scale = quantize_weight(linear.weight, bits=bits, axis=1)
            scale = scale.reshape(-1)
        else:
            from paddle_tpu.quantization.serving import \
                quantize_linear_weight
            q, scale = quantize_linear_weight(unwrap(linear.weight), mode)
        self.register_buffer("qweight", wrap_like(q))
        self.register_buffer("w_scale", wrap_like(scale))
        self.bias = linear.bias
        self.act_scale = act_scale
        self.bits = bits
        self.mode = mode or "int8"
        self.quantized = True   # routing marker (fused-block fallback)

    def forward(self, x):
        from paddle_tpu.ops.pallas.quant_matmul import quant_matmul
        xr = unwrap(x)
        qw = unwrap(self.qweight)
        ws = unwrap(self.w_scale)
        if self.act_scale is not None:
            qmax = 2.0 ** (self.bits - 1) - 1
            xq = jnp.clip(jnp.round(xr / self.act_scale), -qmax - 1,
                          qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, qw, (((xr.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (self.act_scale * ws)
        else:  # weight-only: fused-dequant kernel (fallback off-TPU)
            out = quant_matmul(xr, qw, ws, mode=self.mode)
        if self.bias is not None:
            out = out + unwrap(self.bias)
        return wrap_like(out.astype(xr.dtype))


def _walk_replace(root: Layer, config: QuantConfig, make):
    from paddle_tpu.nn.common_layers import Linear
    for name, child in list(root.named_children()):
        if config.should_quantize(child) and isinstance(child, Linear):
            setattr(root, name, make(child))
        else:
            _walk_replace(child, config, make)


class PTQ:
    """Post-training quantization (reference ptq.py): wrap → calibrate
    (observers collect act ranges) → convert (int8 layers)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        self._observers: Dict[int, MovingAverageAbsMaxObserver] = {}

        def make(linear):
            wrapper = FakeQuantLinear(linear, quant_act=True)
            # PTQ calibration: observe only, don't fake-quant weights yet
            obs = wrapper.act_observer

            class _Calib(Layer):
                def __init__(self):
                    super().__init__()
                    self.inner = linear
                    self.obs = obs

                def forward(self, x):
                    self.obs.observe(x)
                    return self.inner(x)
            c = _Calib()
            self._observers[id(linear)] = obs
            c._ptq_target = linear
            return c
        _walk_replace(model, self.config, make)
        return model

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        def unwrap_calib(root):
            for name, child in list(root.named_children()):
                if hasattr(child, "_ptq_target"):
                    linear = child._ptq_target
                    setattr(root, name, QuantedLinear(
                        linear, act_scale=child.obs.scale()))
                else:
                    unwrap_calib(child)
        unwrap_calib(model)
        return model


class QAT:
    """Quantization-aware training (reference qat.py): insert fake-quant
    wrappers; after training, convert to int8 inference layers."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        _walk_replace(self.config and model, self.config,
                      lambda lin: FakeQuantLinear(lin))
        return model

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        def conv(root):
            for name, child in list(root.named_children()):
                if isinstance(child, FakeQuantLinear):
                    setattr(root, name, QuantedLinear(
                        child.linear, act_scale=child.act_observer.scale()
                        if child.quant_act else None))
                else:
                    conv(child)
        conv(model)
        return model


# serving-time subsystem (lazy-importable as paddle_tpu.quantization.serving;
# re-exported here for the documented public surface)
from paddle_tpu.quantization.serving import (  # noqa: E402
    quant_weights_mode, quantize_for_serving, restore_from_serving)
