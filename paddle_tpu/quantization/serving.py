"""Quantized serving — weight-only int8/fp8 conversion + parity gate.

The serving tentpole behind ``PADDLE_TPU_QUANT_WEIGHTS=int8|fp8``
(ROADMAP item 4): replica HBM is dominated by bf16 weights and the
paged-KV pool, so weight-only quantization roughly doubles the model
capacity a chip can hold — and decode, a bandwidth-bound workload,
reads half the weight bytes per step.

* :func:`quantize_for_serving` — walk a model, replace every large
  ``Linear`` with a weight-only :class:`~paddle_tpu.quantization.
  QuantedLinear` (int8 or ``float8_e4m3fn`` values at rest, one fp32
  scale per output channel).  The converted layers' matmuls route
  through the Pallas quant kernel (``ops/pallas/quant_matmul.py`` —
  dequant fused into the fp32 MXU accumulator) on TPU and its
  numerically-identical jnp fallback elsewhere.  Conversion is
  refcounted: N serving engines can adopt the same model and the last
  :func:`restore_from_serving` puts the original Linears back.
* :func:`parity_report` — the accuracy gate's logit half: one forward
  of the same ids through the original and the converted model,
  reporting max absolute / relative logit error.  ``bench_serve
  --check-equivalence`` combines it with the greedy token-match rate
  into the hard CI threshold.

The serving engine (``inference/serving.py``) reads the knob at
construction: unset reproduces the exact previous engine (knob-off
jaxpr regression-tested, like ``PADDLE_TPU_FUSED_BLOCK``).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["quant_weights_mode", "quantize_linear_weight",
           "quantize_for_serving", "restore_from_serving",
           "parity_report", "QUANT_MODES"]

QUANT_MODES = ("int8", "fp8")

# fp8 e4m3fn: largest finite magnitude (no inf encoding — that's the
# "fn"); symmetric absmax scaling maps the channel max onto it
_FP8_MAX = 448.0


def quant_weights_mode(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the weight-quant mode: an explicit ctor value wins, else
    the ``PADDLE_TPU_QUANT_WEIGHTS`` env knob.  Returns ``"int8"``,
    ``"fp8"`` or None (off — the exact previous behavior)."""
    raw = explicit if explicit is not None \
        else os.environ.get("PADDLE_TPU_QUANT_WEIGHTS")
    if raw is None:
        return None
    raw = str(raw).strip().lower()
    if raw in ("", "0", "off", "none", "false"):
        return None
    if raw not in QUANT_MODES:
        raise ValueError(
            f"PADDLE_TPU_QUANT_WEIGHTS={raw!r}: expected int8|fp8 "
            "(or unset/0 for the bf16 engine)")
    return raw


def quantize_linear_weight(w, mode: str):
    """Symmetric per-output-channel quantization of a ``[in, out]``
    linear weight.  Returns ``(qw, scale)``: ``qw`` in the mode's
    storage dtype, ``scale`` ``[out]`` fp32 such that
    ``dequant = qw * scale``."""
    from paddle_tpu.ops.pallas.quant_matmul import weight_dtype
    wf = jnp.asarray(w).astype(jnp.float32)
    qmax = 127.0 if mode == "int8" else _FP8_MAX
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=0), 1e-12) / qmax
    scaled = wf / scale[None, :]
    if mode == "int8":
        q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    else:
        q = scaled.astype(weight_dtype("fp8"))
    return q, scale.astype(jnp.float32)


def _eligible(linear, min_size: int) -> bool:
    w = getattr(linear, "weight", None)
    if w is None:
        return False
    shape = tuple(w.shape)
    if len(shape) != 2:
        return False
    n = 1
    for d in shape:
        n *= int(d)
    return n >= min_size


def quantize_for_serving(model, mode: Optional[str] = None,
                         min_size: int = 4096) -> Dict[str, int]:
    """Convert every eligible ``Linear`` (2-D weight with >= `min_size`
    elements) into a weight-only :class:`QuantedLinear` IN PLACE.

    Refcounted: converting an already-converted model only bumps the
    refcount, so a fleet of engines can share one model;
    :func:`restore_from_serving` restores the original Linears when the
    last holder lets go (each QuantedLinear keeps its source layer on
    ``_orig`` — serving keeps the fp weights host-side for restore; a
    deployment that wants them gone converts once and never restores).

    Returns ``{"layers": n_converted, "refs": current_refcount}``.
    """
    mode = quant_weights_mode(mode)
    if mode is None:
        raise ValueError("quantize_for_serving needs mode=int8|fp8 "
                         "(or PADDLE_TPU_QUANT_WEIGHTS set)")
    refs = getattr(model, "_serving_quant_refs", 0)
    if refs > 0:
        if getattr(model, "_serving_quant_mode", None) != mode:
            raise ValueError(
                f"model already quantized for serving as "
                f"{model._serving_quant_mode!r}; cannot re-quantize as "
                f"{mode!r} while {refs} engine(s) hold it")
        model._serving_quant_refs = refs + 1
        return {"layers": model._serving_quant_layers, "refs": refs + 1}

    from paddle_tpu.nn.common_layers import Linear
    from paddle_tpu.quantization import QuantedLinear

    converted = [0]

    def walk(root):
        for name, child in list(root.named_children()):
            if isinstance(child, Linear) and _eligible(child, min_size):
                q = QuantedLinear(child, act_scale=None, mode=mode)
                q._orig = child
                setattr(root, name, q)
                converted[0] += 1
            else:
                walk(child)

    walk(model)
    model._serving_quant_refs = 1
    model._serving_quant_mode = mode
    model._serving_quant_layers = converted[0]
    return {"layers": converted[0], "refs": 1}


def restore_from_serving(model) -> bool:
    """Drop one conversion reference; when it is the last, swap every
    QuantedLinear back to its original Linear.  Returns True when the
    model is back in its original form."""
    refs = getattr(model, "_serving_quant_refs", 0)
    if refs == 0:
        return True
    if refs > 1:
        model._serving_quant_refs = refs - 1
        return False

    from paddle_tpu.quantization import QuantedLinear

    def walk(root):
        for name, child in list(root.named_children()):
            if isinstance(child, QuantedLinear) and \
                    getattr(child, "_orig", None) is not None:
                setattr(root, name, child._orig)
            else:
                walk(child)

    walk(model)
    model._serving_quant_refs = 0
    model._serving_quant_mode = None
    return True


def parity_report(model, mode: str, sample_ids,
                  min_size: int = 4096) -> Dict[str, float]:
    """Logit half of the accuracy-parity gate: forward `sample_ids`
    (``[B, S]`` int32) through the model before and after weight-only
    conversion and report the divergence.  The model is restored before
    returning, whatever happens.

    Returns ``{max_logit_err, ref_logit_absmax, rel_logit_err,
    layers}`` — ``rel_logit_err`` (max abs error over the reference's
    absmax) is the number the CI threshold bounds."""
    from paddle_tpu.core.dispatch import unwrap

    ids = np.asarray(sample_ids, np.int32)
    if ids.ndim == 1:
        ids = ids[None]
    was_training = getattr(model, "training", False)
    if was_training:
        model.eval()
    try:
        ref = np.asarray(unwrap(model(ids)), np.float32)
        info = quantize_for_serving(model, mode, min_size=min_size)
        try:
            got = np.asarray(unwrap(model(ids)), np.float32)
        finally:
            restore_from_serving(model)
    finally:
        if was_training:
            model.train()
    err = float(np.abs(got - ref).max())
    absmax = float(np.abs(ref).max())
    return {"max_logit_err": err,
            "ref_logit_absmax": absmax,
            "rel_logit_err": err / max(absmax, 1e-12),
            "layers": info["layers"]}
