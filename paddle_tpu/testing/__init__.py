"""OpTest — declarative numeric op-testing harness.

Rebuild of the reference's highest-leverage test framework
(test/legacy_test/eager_op_test.py: class OpTest :377, check_output :2143,
check_grad vs finite differences :2325, numeric grad :133): declare numpy
inputs/attrs once; the harness checks every execution mode and the
gradients against central finite differences with per-dtype tolerances.

Modes checked by ``check_output``:
  * eager     — Tensor inputs through the dispatch tape
  * jit       — the op under jax.jit on raw arrays
  * functional— raw jax arrays (no Tensor wrapper), the in-trace path

Gradient checks (``check_grad``):
  * eager tape (Tensor.backward) and jax.grad both vs central differences

Usage:
    class TestAdd(OpTest):
        def setup(self):
            self.op = paddle_tpu.add
            self.inputs = {"x": rand(3, 4), "y": rand(3, 4)}
            self.ref = np.add          # numpy oracle
    # or the compact spec form:
    make_op_test(op=pp.add, ref=np.add, n_inputs=2)
"""

from __future__ import annotations

import zlib
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

__all__ = ["OpTest", "op_case", "binary_cases", "unary_cases"]

# per-dtype (rtol, atol) — mirrors the reference's per-dtype thresholds
# (op_accuracy_white_list / check_output atol args).  CPU XLA matmuls run
# in reduced precision by default, so fp32 tolerances are not 1e-7.
_TOL = {
    np.dtype(np.float64): (1e-7, 1e-7),
    np.dtype(np.float32): (1e-5, 1e-6),
    np.dtype(np.float16): (1e-2, 1e-3),
    # bf16 ~ 8 mantissa bits
    "bfloat16": (2e-2, 2e-2),
}


def _tol_for(dtype, rtol=None, atol=None):
    key = "bfloat16" if str(dtype) == "bfloat16" else np.dtype(dtype)
    base_r, base_a = _TOL.get(key, (1e-5, 1e-6))
    return (rtol if rtol is not None else base_r,
            atol if atol is not None else base_a)


def _to_np(x):
    import jax.numpy as jnp
    if hasattr(x, "_data"):
        x = x._data
    if hasattr(x, "dtype") and x.dtype == jnp.bfloat16:
        return np.asarray(x.astype(jnp.float32))
    return np.asarray(x)


def _assert_close(got, want, rtol, atol, what):
    got, want = _to_np(got), _to_np(want)
    assert got.shape == tuple(np.shape(want)), \
        f"{what}: shape {got.shape} != {np.shape(want)}"
    if got.size == 0:
        return
    if got.dtype == bool or np.issubdtype(got.dtype, np.integer):
        np.testing.assert_array_equal(got, want, err_msg=what)
    else:
        np.testing.assert_allclose(got, np.asarray(want, got.dtype),
                                   rtol=rtol, atol=atol, err_msg=what)


class OpTest:
    """Subclass, implement setup(), get all modes + grads checked.

    Attributes set by setup():
      op:       the paddle_tpu op (eager_op-wrapped callable)
      inputs:   {name: np.ndarray} tensor inputs (ordered — passed
                positionally in declaration order)
      attrs:    {name: value} non-tensor kwargs
      ref:      numpy oracle fn(*inputs_np, **attrs) -> array / tuple
      grad_inputs: names to gradient-check (default: float inputs)
      out_index: when the op returns a tuple, which element to check
                 gradients through (default 0)
    """

    op: Callable = None
    inputs: Dict[str, np.ndarray] = None
    attrs: Dict[str, Any] = None
    ref: Callable = None
    grad_inputs: Optional[Sequence[str]] = None
    out_index: int = 0
    rtol: Optional[float] = None
    atol: Optional[float] = None
    # max relative error for finite-difference grad comparison
    # (reference default max_relative_error=0.005; FD in f32 is noisy)
    grad_rtol: float = 1e-2
    fd_eps: float = 1e-2

    def setup(self):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------
    def _pure(self):
        """The op on raw jax arrays (bypassing the Tensor tape)."""
        op = self.op
        attrs = self.attrs or {}

        def fn(*arrays):
            out = op(*arrays, **attrs)
            return out

        return fn

    def _ref_out(self):
        vals = [v for v in self.inputs.values()]
        out = self.ref(*vals, **(self.attrs or {}))
        return out

    # -- checks ------------------------------------------------------------
    def check_output(self):
        import jax
        import jax.numpy as jnp
        import paddle_tpu as pp

        self.setup()
        attrs = self.attrs or {}
        names = list(self.inputs)
        arrays = [jnp.asarray(self.inputs[n]) for n in names]
        want = self._ref_out()
        multi = isinstance(want, (tuple, list))

        dtype = arrays[0].dtype if arrays else np.float32
        rtol, atol = _tol_for(dtype, self.rtol, self.atol)

        def compare(out, mode):
            if multi:
                for i, w in enumerate(want):
                    if w is None:
                        continue
                    _assert_close(out[i], w, rtol, atol,
                                  f"{self._opname()}[{mode}] out{i}")
            else:
                _assert_close(out, want, rtol, atol,
                              f"{self._opname()}[{mode}]")

        # eager (Tensor) mode
        tens = [pp.to_tensor(self.inputs[n]) for n in names]
        compare(self.op(*tens, **attrs), "eager")
        # functional (raw) mode
        compare(self.op(*arrays, **attrs), "functional")
        # jit mode
        compare(jax.jit(self._pure())(*arrays), "jit")

    def check_grad(self):
        """Analytic grads (eager tape AND jax.grad) vs central differences,
        through a scalar projection loss sum(out * w) with fixed random w
        (the reference uses uniform dout; a random projection catches
        sign/transpose errors plain sums miss)."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu as pp

        self.setup()
        attrs = self.attrs or {}
        names = list(self.inputs)
        which = list(self.grad_inputs if self.grad_inputs is not None else
                     [n for n in names
                      if np.issubdtype(np.asarray(self.inputs[n]).dtype,
                                       np.floating)])
        if not which:
            return
        arrays = [jnp.asarray(self.inputs[n]) for n in names]

        rng = np.random.default_rng(0)
        out_probe = self._pure()(*arrays)
        if isinstance(out_probe, tuple):
            out_probe = out_probe[self.out_index]
        w = jnp.asarray(rng.standard_normal(out_probe.shape),
                        out_probe.dtype) if out_probe.size else \
            jnp.zeros(out_probe.shape, out_probe.dtype)

        idx = self.out_index

        def scalar_loss(*arrays_):
            out = self._pure()(*arrays_)
            if isinstance(out, tuple):
                out = out[idx]
            return jnp.sum(out.astype(jnp.float32)
                           * w.astype(jnp.float32))

        argnums = tuple(names.index(n) for n in which)
        analytic = jax.grad(scalar_loss, argnums=argnums)(*arrays)

        # eager-tape grads for the same projection
        tens = [pp.to_tensor(self.inputs[n]) for n in names]
        for t, n in zip(tens, names):
            t.stop_gradient = n not in which
        out_t = self.op(*tens, **attrs)
        if isinstance(out_t, (tuple, list)):
            out_t = out_t[idx]
        loss_t = (out_t.astype("float32") * pp.to_tensor(np.asarray(w))
                  ).sum()
        loss_t.backward()

        for n, g_an in zip(which, analytic):
            x_np = np.asarray(self.inputs[n], np.float32)
            i = names.index(n)
            g_fd = self._numeric_grad(scalar_loss, arrays, i, x_np)
            g_an = _to_np(g_an)
            self._compare_grads(g_an, g_fd, f"{self._opname()} d/d{n} "
                                            f"(jax.grad vs FD)")
            g_tape = tens[i].grad
            if g_tape is not None:
                self._compare_grads(_to_np(g_tape), g_an,
                                    f"{self._opname()} d/d{n} "
                                    f"(tape vs jax.grad)", tight=True)

    def _numeric_grad(self, loss, arrays, i, x_np):
        """Central differences, one element at a time (reference
        get_numeric_gradient :133)."""
        import jax.numpy as jnp
        eps = self.fd_eps
        flat = x_np.reshape(-1).copy()
        g = np.zeros_like(flat, np.float64)
        for j in range(flat.size):
            orig = flat[j]
            for sign, store in ((1.0, 0), (-1.0, 1)):
                flat[j] = orig + sign * eps
                arrs = list(arrays)
                arrs[i] = jnp.asarray(flat.reshape(x_np.shape),
                                      arrays[i].dtype)
                val = float(loss(*arrs))
                if store == 0:
                    plus = val
                else:
                    minus = val
            g[j] = (plus - minus) / (2 * eps)
            flat[j] = orig
        return g.reshape(x_np.shape)

    def _compare_grads(self, got, want, what, tight=False):
        got = np.asarray(got, np.float64).reshape(-1)
        want = np.asarray(want, np.float64).reshape(-1)
        if got.size == 0:
            return
        if tight:
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5,
                                       err_msg=what)
            return
        # reference-style max relative error against max(|grad|, 1)
        denom = np.maximum(np.abs(want).max(), 1.0)
        max_err = np.abs(got - want).max() / denom
        assert max_err < self.grad_rtol, \
            f"{what}: max relative grad error {max_err:.3e} " \
            f">= {self.grad_rtol}"

    def _opname(self):
        return getattr(self.op, "__name__", str(self.op))

    def run(self, grad=True):
        self.check_output()
        if grad:
            self.check_grad()


# -- compact spec helpers ----------------------------------------------------

class op_case(OpTest):
    """One-liner OpTest: op_case(op, ref, inputs, attrs=..., ...).run()"""

    def __init__(self, op, ref, inputs, attrs=None, grad_inputs=None,
                 rtol=None, atol=None, grad_rtol=None, out_index=0,
                 fd_eps=None):
        self._spec = dict(op=op, ref=ref, inputs=inputs, attrs=attrs or {},
                          grad_inputs=grad_inputs, rtol=rtol, atol=atol,
                          out_index=out_index)
        if grad_rtol is not None:
            self.grad_rtol = grad_rtol
        if fd_eps is not None:
            self.fd_eps = fd_eps

    def setup(self):
        for k, v in self._spec.items():
            setattr(self, k, v)


def _rand(shape, dtype=np.float32, lo=-1.0, hi=1.0, seed=None):
    # deterministic across interpreter runs (hash() is salted per process)
    if seed is None:
        seed = zlib.crc32(repr((tuple(shape), str(dtype))).encode())
    rng = np.random.default_rng(seed)
    return (rng.uniform(lo, hi, shape)).astype(dtype)


def binary_cases(op, ref, lo=-1.0, hi=1.0, grad=True, dtypes=(np.float32,),
                 grad_rtol=None):
    """Standard shape sweep for a binary elementwise op: same-shape,
    broadcast, scalar-operand, 0-size (the reference's degenerate-shape
    coverage)."""
    shapes = [((3, 4), (3, 4)), ((2, 3, 4), (3, 4)), ((3, 1), (1, 4)),
              ((4,), ()), ((0, 3), (0, 3))]
    cases = []
    for dt in dtypes:
        for sx, sy in shapes:
            cases.append(op_case(
                op, ref,
                {"x": _rand(sx, dt, lo, hi), "y": _rand(sy, dt, lo, hi)},
                grad_inputs=None if grad else [], grad_rtol=grad_rtol))
    return cases


def unary_cases(op, ref, lo=-1.0, hi=1.0, grad=True, dtypes=(np.float32,),
                grad_rtol=None, fd_eps=None):
    shapes = [(3, 4), (2, 3, 4), (), (0, 4)]
    cases = []
    for dt in dtypes:
        for s in shapes:
            cases.append(op_case(
                op, ref, {"x": _rand(s, dt, lo, hi)},
                grad_inputs=None if grad else [], grad_rtol=grad_rtol,
                fd_eps=fd_eps))
    return cases
