"""paddle.audio.features parity: Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers.

Reference: python/paddle/audio/features/layers.py:24,106,206,309.
TPU-native: framing is a strided reshape-gather, the FFT is jnp.fft.rfft
(XLA's native FFT on TPU), everything below is matmuls against
precomputed filterbank/DCT matrices — MXU food.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import eager_op, unwrap, wrap_like
from paddle_tpu.nn.layer import Layer
from paddle_tpu.audio import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center, pad_mode):
    if center:
        pad = frame_length // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    n = x.shape[-1]
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]  # [..., n_frames, frame_length]


@eager_op
def _spectrogram_raw(x, window, n_fft, hop_length, power, center,
                     pad_mode):
    frames = _frame(x, n_fft, hop_length, center, pad_mode)
    spec = jnp.fft.rfft(frames * window, axis=-1)
    mag = jnp.abs(spec)
    out = mag if power == 1.0 else mag ** power
    return jnp.swapaxes(out, -1, -2)  # [..., freq, time]


@eager_op
def _apply_filterbank(spec, fbank):
    return jnp.einsum("mf,...ft->...mt", fbank, spec)


@eager_op
def _apply_dct(logmel, dct):
    return jnp.einsum("mk,...mt->...kt", dct, logmel)


class Spectrogram(Layer):
    """STFT magnitude/power spectrogram (reference layers.py:24)."""

    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        win_length = win_length or n_fft
        w = unwrap(AF.get_window(window, win_length))
        if win_length < n_fft:  # centre-pad the window to n_fft
            lpad = (n_fft - win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
        self.register_buffer("window", wrap_like(w))

    def forward(self, x):
        return _spectrogram_raw(x, self.window, self.n_fft,
                                self.hop_length, self.power, self.center,
                                self.pad_mode)


class MelSpectrogram(Layer):
    """Spectrogram -> mel filterbank (reference layers.py:106)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm: str = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode)
        fb = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                     norm)
        self.register_buffer("fbank_matrix", fb)

    def forward(self, x):
        # stays on the dispatcher so the eager tape flows end to end
        return _apply_filterbank(self._spectrogram(x), self.fbank_matrix)


class LogMelSpectrogram(Layer):
    """reference layers.py:206."""

    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm: str = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db=None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (reference layers.py:309)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length=None, win_length=None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max=None, htk: bool = False,
                 norm: str = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        self.register_buffer("dct_matrix", AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        return _apply_dct(self._log_melspectrogram(x), self.dct_matrix)
