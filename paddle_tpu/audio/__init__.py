"""paddle.audio parity: functional mel/window math + feature layers."""

from paddle_tpu.audio import datasets  # noqa: F401
from paddle_tpu.audio import features  # noqa: F401
from paddle_tpu.audio import functional  # noqa: F401

__all__ = ["datasets", "features", "functional"]
