"""paddle.audio.datasets — audio classification datasets.

Reference parity: ``python/paddle/audio/datasets`` (ESC50/TESS —
AudioClassificationDataset subclasses that download archives and return
(waveform, label) pairs, esc50.py:26 / tess.py).  Same stance as
vision/text datasets in this repo (zero-egress environment): a
DETERMINISTIC SYNTHETIC backend generates class-dependent waveforms
(per-class harmonic stacks + seeded noise) with the reference's shapes,
label sets, and (mode, split) semantics.  Sizes are scaled down from the
reference archives (ESC50 500 vs 2000 clips, TESS 280 vs 2800) — enough
to exercise pipelines without minute-long synthetic generation.  Passing
``data_path``/``archive`` (the real-data knobs) raises: wiring real
extracted archives is out of scope for this zero-egress build.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["ESC50", "TESS"]


class _SyntheticAudioClasses(Dataset):
    """Class k = a k-dependent chord (fundamental + 2 harmonics) plus
    seeded noise — separable, deterministic, no downloads.

    Fold semantics mirror the reference: items live in `n_folds` folds;
    mode 'train' serves every fold except `split`, mode 'dev' serves fold
    `split` only — so train/dev are DISJOINT for a given split and
    rotating `split` rotates which items are held out.
    """

    def __init__(self, mode: str, n_folds: int, split: int, per_fold: int,
                 num_classes: int, sample_rate: int, duration: float,
                 feat_type: str = "raw", archive=None,
                 data_path: Optional[str] = None, seed: int = 0,
                 **feat_kwargs):
        if mode not in ("train", "dev"):
            raise ValueError(f"mode must be 'train' or 'dev', got {mode!r}")
        if not 1 <= split <= n_folds:
            raise ValueError(f"split must be 1..{n_folds}, got {split}")
        if data_path is not None or archive is not None:
            raise NotImplementedError(
                "real-archive loading is not wired in this zero-egress "
                "build; the synthetic backend serves the same surface")
        # global item ids partitioned into folds; train = all other folds
        folds = [f for f in range(1, n_folds + 1) if
                 (f != split if mode == "train" else f == split)]
        self._ids = [(f - 1) * per_fold + i for f in folds
                     for i in range(per_fold)]
        self._classes = num_classes
        self._sr = sample_rate
        self._len = int(sample_rate * duration)
        self._seed = seed
        self._featurizer = self._make_featurizer(feat_type, feat_kwargs)

    def _make_featurizer(self, feat_type: str, kwargs):
        if feat_type == "raw":
            return None
        from paddle_tpu.audio import features as AF
        layers = {"melspectrogram": AF.MelSpectrogram,
                  "mfcc": AF.MFCC,
                  "spectrogram": AF.Spectrogram,
                  "logmelspectrogram": AF.LogMelSpectrogram}
        if feat_type not in layers:
            raise ValueError(f"unknown feat_type {feat_type!r}; "
                             f"choose from raw/{'/'.join(layers)}")
        if feat_type == "spectrogram":
            return layers[feat_type](**kwargs)  # no sr parameter
        return layers[feat_type](sr=self._sr, **kwargs)

    def __len__(self):
        return len(self._ids)

    def __getitem__(self, idx):
        gid = self._ids[idx]
        label = gid % self._classes
        rng = np.random.default_rng(self._seed * 100003 + gid)
        t = np.arange(self._len) / self._sr
        f0 = 110.0 * (1 + label)
        wave = sum(0.5 / (h + 1) * np.sin(2 * np.pi * f0 * (h + 1) * t
                                          + rng.uniform(0, 2 * np.pi))
                   for h in range(3))
        wave = (wave + 0.05 * rng.standard_normal(self._len)) \
            .astype(np.float32)
        if self._featurizer is None:
            return wave, np.int64(label)
        import jax.numpy as jnp
        from paddle_tpu.core.dispatch import unwrap
        out = self._featurizer(jnp.asarray(wave)[None, :])
        return np.asarray(unwrap(out))[0], np.int64(label)


class ESC50(_SyntheticAudioClasses):
    """Environmental Sound Classification (reference esc50.py:26 — 50
    classes, 5 folds, 5 s @ 44.1 kHz clips)."""

    n_folds = 5
    sample_rate = 44100
    duration = 5.0
    num_classes = 50

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", **kwargs):
        super().__init__(mode, self.n_folds, split, per_fold=100,
                         num_classes=self.num_classes,
                         sample_rate=self.sample_rate,
                         duration=self.duration, feat_type=feat_type,
                         **kwargs)


class TESS(_SyntheticAudioClasses):
    """Toronto Emotional Speech Set (reference tess.py — 7 emotions,
    ~2.1 s @ 24.414 kHz)."""

    sample_rate = 24414
    duration = 2.1
    num_classes = 7

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw", **kwargs):
        super().__init__(mode, n_folds, split, per_fold=56,
                         num_classes=self.num_classes,
                         sample_rate=self.sample_rate,
                         duration=self.duration, feat_type=feat_type,
                         **kwargs)
