"""paddle.audio.functional parity: mel math, filterbanks, windows, dB.

Reference: python/paddle/audio/functional/functional.py (hz_to_mel :22,
mel_to_hz :78, mel_frequencies :123, fft_frequencies :163,
compute_fbank_matrix :186, power_to_db :259, create_dct :303) and
window.py get_window.  TPU-native: plain jnp math; spectrogram framing
uses XLA's strided gather (conv-free), FFT via jnp.fft.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dispatch import eager_op, unwrap, wrap_like

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk: bool = False):
    """Hertz -> mel (Slaney by default; htk=True for the HTK formula)."""
    f = unwrap(freq)
    scalar = not hasattr(f, "shape") or jnp.ndim(f) == 0
    f = jnp.asarray(f, jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                              / min_log_hz) / logstep,
                        mels)
    return float(out) if scalar else wrap_like(out)


def mel_to_hz(mel, htk: bool = False):
    m = unwrap(mel)
    scalar = not hasattr(m, "shape") or jnp.ndim(m) == 0
    m = jnp.asarray(m, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    return float(out) if scalar else wrap_like(out)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return wrap_like(unwrap(mel_to_hz(wrap_like(mels), htk)))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    return wrap_like(jnp.linspace(0, sr / 2, n_fft // 2 + 1,
                                  dtype=jnp.float32))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm: str = "slaney", dtype: str = "float32"):
    """[n_mels, n_fft//2+1] triangular mel filterbank
    (reference functional.py:186)."""
    if f_max is None:
        f_max = sr / 2.0
    fft_f = unwrap(fft_frequencies(sr, n_fft))
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mel_pts = unwrap(mel_to_hz(wrap_like(
        jnp.linspace(lo, hi, n_mels + 2)), htk))
    fdiff = jnp.diff(mel_pts)
    ramps = mel_pts[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_pts[2:n_mels + 2] - mel_pts[:n_mels])
        weights = weights * enorm[:, None]
    return wrap_like(weights.astype(jnp.float32))


@eager_op
def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    """10*log10(S/ref) with floor (reference functional.py:259)."""
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, spect))
    log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc: int, n_mels: int, norm="ortho",
               dtype: str = "float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference functional.py:303)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    basis = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis = basis * jnp.where(k == 0, 1.0 / math.sqrt(n_mels),
                                  math.sqrt(2.0 / n_mels))
    else:
        basis = basis * 2.0
    return wrap_like(basis.astype(jnp.float32))


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype: str = "float32"):
    """Window function by name (reference window.py get_window);
    periodic (fftbins=True) or symmetric."""
    M = win_length + 1 if fftbins else win_length
    n = np.arange(M, dtype=np.float64)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / (M - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / (M - 1))
             + 0.08 * np.cos(4 * np.pi * n / (M - 1)))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(M)
    elif window == "bartlett":
        w = 1.0 - np.abs(2 * n / (M - 1) - 1.0)
    else:
        raise ValueError(f"unsupported window {window!r}")
    if fftbins:
        w = w[:-1]
    return wrap_like(jnp.asarray(w.astype(np.float32)))
