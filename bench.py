"""Benchmark: Llama pretraining MFU on the available chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline (BASELINE.md): Llama-3-8B pretraining >= 40% MFU on v5p; on a single
chip we measure a Llama-proportioned model that fits one chip's HBM and
report model FLOPs utilisation of the full fwd+bwd+update step.

The ``detail`` payload carries the device-observability evidence next to
the headline: AOT compile-phase times and the executable's XLA-measured
FLOPs / bytes / peak HBM, plus the device-profiler's roofline-gap
attribution (the ranked fusion target list) and the live-byte watermark.
``--compare`` re-checks the fresh run against the newest BENCH_r*.json:
a headline drop (or step-time rise) beyond ``--tolerance`` prints a
``bench_compare`` line to stderr and exits 1.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

import numpy as np

# bf16 peak FLOP/s per chip by TPU generation
_PEAK = {
    "v4": 275e12,
    "v5 lite": 197e12, "v5e": 197e12,
    "v5": 459e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12, "trillium": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return 459e12  # assume v5p (the baseline hardware)


def _prev_record():
    """Parsed payload of the latest successful BENCH_r*.json (headline +
    detail), so fresh runs can be compared against trajectory."""
    best_round, best = -1, None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            parsed = rec.get("parsed") or {}
            val = parsed.get("value")
        except Exception:
            continue
        if val is not None and int(m.group(1)) > best_round:
            best_round, best = int(m.group(1)), parsed
    return best


def _prev_value():
    prev = _prev_record()
    return float(prev["value"]) if prev else None


def _prev_serve_record():
    """Parsed payload of the latest BENCH_serve_r*.json — the serving
    trajectory's newest point (bench_serve.py emits them)."""
    best_round, best = -1, None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, "BENCH_serve_r*.json")):
        m = re.search(r"BENCH_serve_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            parsed = rec.get("parsed") or rec   # raw result files too
            val = parsed.get("value")
        except Exception:
            continue
        if val is not None and int(m.group(1)) > best_round:
            best_round, best = int(m.group(1)), parsed
    return best


def compare_serve_records(cur: dict, prev: dict, tolerance: float = 0.25):
    """Serving regression check: tokens/s (headline value) is
    better-higher; TTFT/TPOT p99 latencies are better-lower.  Returns
    human-readable regression strings (empty = within tolerance).  The
    default tolerance is wider than training's — serving latency on a
    shared CI host is noisier than a dedicated chip's step time."""
    regressions = []
    pv, cv = prev.get("value"), cur.get("value")
    if pv and cv is not None and cv < float(pv) * (1.0 - tolerance):
        regressions.append(
            f"tokens_per_s {cv:.2f} < prev {float(pv):.2f} - "
            f"{tolerance:.0%} tolerance (ratio {cv / float(pv):.3f})")
    pd = prev.get("detail") or {}
    cd = cur.get("detail") or {}
    for key in ("ttft_p99_s", "tpot_p99_s"):
        pl, cl = pd.get(key), cd.get(key)
        if pl and cl and float(cl) > float(pl) * (1.0 + tolerance):
            regressions.append(
                f"{key} {float(cl):.4f} > prev {float(pl):.4f} + "
                f"{tolerance:.0%} tolerance")
    # replica cold-start (both artifacts must carry the section)
    pw = (pd.get("cold_start") or {}).get("warmup_wall_s")
    cw = (cd.get("cold_start") or {}).get("warmup_wall_s")
    if pw and cw and float(cw) > float(pw) * (2.0 + tolerance):
        regressions.append(
            f"cold_start.warmup_wall_s {float(cw):.4f} > prev "
            f"{float(pw):.4f} x (2 + {tolerance:.0%})")
    # SLO attainment (better-higher fractions; guarded once both
    # artifacts carry the section AND judged against the same target)
    ps, cs = pd.get("slo_attainment") or {}, cd.get("slo_attainment") or {}
    for kind in ("ttft", "tpot"):
        pa, ca = ps.get(kind), cs.get(kind)
        same_target = ps.get(f"{kind}_target_s") == cs.get(
            f"{kind}_target_s")
        if pa and ca is not None and same_target and \
                float(ca) < float(pa) * (1.0 - tolerance):
            regressions.append(
                f"slo_attainment.{kind} {float(ca):.3f} < prev "
                f"{float(pa):.3f} - {tolerance:.0%} tolerance")
    # quantized serving (guarded once both artifacts ran the same
    # quant modes): the capacity ratio must not shrink and the parity
    # gate's token-match rate is better-higher — quantization can
    # never silently rot quality between rounds
    pq, cq = pd.get("quant") or {}, cd.get("quant") or {}
    if pq and cq and pq.get("weights") == cq.get("weights") and \
            pq.get("kv") == cq.get("kv"):
        pr, cr = pq.get("kv_blocks_ratio"), cq.get("kv_blocks_ratio")
        if pr and cr is not None and float(cr) < float(pr):
            regressions.append(
                f"quant.kv_blocks_ratio {float(cr):.2f} < prev "
                f"{float(pr):.2f}")
        pm, cm = pq.get("token_match_rate"), cq.get("token_match_rate")
        if pm and cm is not None and \
                float(cm) < float(pm) * (1.0 - tolerance):
            regressions.append(
                f"quant.token_match_rate {float(cm):.4f} < prev "
                f"{float(pm):.4f} - {tolerance:.0%} tolerance")
    # fleet serving (router speedup over the in-process single-engine
    # baseline is better-higher; guarded once both artifacts ran
    # --fleet with the same replica count)
    pf, cf = pd.get("fleet") or {}, cd.get("fleet") or {}
    if pf.get("speedup") and cf.get("speedup") is not None and \
            pf.get("replicas") == cf.get("replicas"):
        if float(cf["speedup"]) < float(pf["speedup"]) \
                * (1.0 - tolerance):
            regressions.append(
                f"fleet.speedup {float(cf['speedup']):.3f} < prev "
                f"{float(pf['speedup']):.3f} - {tolerance:.0%} "
                "tolerance")
    # session survivability (guarded once both artifacts ran
    # --sessions): the resident-sessions-over-HBM-capacity ratio is
    # better-higher and must not shrink beyond tolerance, and resumed
    # sessions must stay token-identical — parking can never trade
    # capacity for wrong tokens
    psess, csess = pd.get("sessions") or {}, cd.get("sessions") or {}
    if psess and csess:
        pr = psess.get("sessions_resident_ratio")
        cr = csess.get("sessions_resident_ratio")
        if pr and cr is not None and \
                float(cr) < float(pr) * (1.0 - tolerance):
            regressions.append(
                f"sessions.sessions_resident_ratio {float(cr):.2f} < "
                f"prev {float(pr):.2f} - {tolerance:.0%} tolerance")
        if csess.get("token_identity") is False:
            regressions.append(
                "sessions.token_identity is False: a resumed session "
                "decoded different tokens")
        if csess.get("recompute_fallback_identity") is False:
            regressions.append(
                "sessions.recompute_fallback_identity is False: the "
                "tier-miss recompute path decoded different tokens")
    # tail attribution (guarded once both artifacts carry the
    # forensics section): the dominant overhead cause flipping between
    # rounds, or the cold-resume share of request overhead growing past
    # tolerance, means the serving tail changed shape — not just got
    # uniformly slower — and deserves a named regression
    pt, ct = pd.get("tail_attribution") or {}, \
        cd.get("tail_attribution") or {}
    if pt and ct:
        pdom, cdom = pt.get("dominant_cause"), ct.get("dominant_cause")
        if pdom and cdom and pdom != cdom and cdom != "none":
            regressions.append(
                f"tail_attribution.dominant_cause flipped "
                f"{pdom} -> {cdom}")
        pcold = pt.get("cold_resume_share")
        ccold = ct.get("cold_resume_share")
        if ccold is not None and \
                float(ccold) > float(pcold or 0.0) + tolerance:
            regressions.append(
                f"tail_attribution.cold_resume_share "
                f"{float(ccold):.3f} > prev {float(pcold or 0.0):.3f} "
                f"+ {tolerance:.2f}")
    regressions += _compare_calibration(cur, prev, tolerance)
    return regressions


def _prev_recovery_record():
    """Parsed payload of the latest BENCH_recovery_r*.json — the
    fast-recovery MTTR trajectory (``--recovery-drill`` emits them)."""
    best_round, best = -1, None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, "BENCH_recovery_r*.json")):
        m = re.search(r"BENCH_recovery_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            parsed = rec.get("parsed") or rec
            val = parsed.get("value")
        except Exception:
            continue
        if val is not None and int(m.group(1)) > best_round:
            best_round, best = int(m.group(1)), parsed
    return best


def _next_recovery_round(here: str) -> int:
    rounds = [int(m.group(1)) for p in
              glob.glob(os.path.join(here, "BENCH_recovery_r*.json"))
              if (m := re.search(r"BENCH_recovery_r(\d+)\.json$", p))]
    return max(rounds, default=0) + 1


def compare_records(cur: dict, prev: dict, tolerance: float = 0.05):
    """Regression check of a fresh result against a previous BENCH
    payload.  Returns a list of human-readable regression strings
    (empty = within tolerance).  Headline value is better-higher;
    step_time_s is better-lower."""
    regressions = []
    pv = prev.get("value")
    cv = cur.get("value")
    if pv and cv is not None and cv < float(pv) * (1.0 - tolerance):
        regressions.append(
            f"value {cv:.4f} < prev {float(pv):.4f} - {tolerance:.0%} "
            f"tolerance (ratio {cv / float(pv):.3f})")
    pt = (prev.get("detail") or {}).get("step_time_s")
    ct = (cur.get("detail") or {}).get("step_time_s")
    if pt and ct and float(ct) > float(pt) * (1.0 + tolerance):
        regressions.append(
            f"step_time_s {float(ct):.4f} > prev {float(pt):.4f} + "
            f"{tolerance:.0%} tolerance")
    # training goodput (better-higher; only once both artifacts carry it)
    pg = ((prev.get("detail") or {}).get("goodput") or {}).get("value")
    cg = ((cur.get("detail") or {}).get("goodput") or {}).get("value")
    if pg and cg is not None and float(cg) < float(pg) * (1.0 - tolerance):
        regressions.append(
            f"goodput {float(cg):.4f} < prev {float(pg):.4f} - "
            f"{tolerance:.0%} tolerance")
    # cold-start trajectory (only once both artifacts carry the section;
    # compile wall time on a shared host is noisy, so the bar is a 2x+
    # blowup past tolerance rather than drift)
    pc = (prev.get("detail") or {}).get("cold_start") or {}
    cc = (cur.get("detail") or {}).get("cold_start") or {}
    pt, ct = pc.get("total_s"), cc.get("total_s")
    if pt and ct and float(ct) > float(pt) * (2.0 + tolerance):
        regressions.append(
            f"cold_start.total_s {float(ct):.4f} > prev {float(pt):.4f} "
            f"x (2 + {tolerance:.0%})")
    # fast-recovery MTTR (lower-is-better; guarded once both artifacts
    # carry the section) — the trajectory guards time-to-recover like
    # any perf number
    pr = (prev.get("detail") or {}).get("recovery") or {}
    cr = (cur.get("detail") or {}).get("recovery") or {}
    pm, cm = pr.get("mttr_s"), cr.get("mttr_s")
    if pm and cm and float(cm) > float(pm) * (1.0 + tolerance):
        regressions.append(
            f"recovery.mttr_s {float(cm):.4f} > prev {float(pm):.4f} + "
            f"{tolerance:.0%} tolerance")
    regressions += _compare_calibration(cur, prev, tolerance)
    return regressions


def _compare_calibration(cur: dict, prev: dict, tolerance: float):
    """Calibration-health trajectory (guarded: only once BOTH artifacts
    carry an enabled ``detail.calibration`` section): ledger coverage is
    better-higher, mean |residual-1| better-lower.  Residuals on a
    shared CPU host are noisy, so the residual bar is a 2x+ blowup past
    tolerance (the cold-start convention), while coverage — a counting
    ratio — uses the plain tolerance."""
    regressions = []
    pc = (prev.get("detail") or {}).get("calibration") or {}
    cc = (cur.get("detail") or {}).get("calibration") or {}
    if not (pc.get("enabled") and cc.get("enabled")):
        return regressions
    pv, cv = pc.get("coverage"), cc.get("coverage")
    if pv and cv is not None and \
            float(cv) < float(pv) * (1.0 - tolerance):
        regressions.append(
            f"calibration.coverage {float(cv):.3f} < prev "
            f"{float(pv):.3f} - {tolerance:.0%} tolerance")
    pv, cv = pc.get("mean_abs_residual"), cc.get("mean_abs_residual")
    if pv and cv and float(cv) > float(pv) * (2.0 + tolerance):
        regressions.append(
            f"calibration.mean_abs_residual {float(cv):.3f} > prev "
            f"{float(pv):.3f} x (2 + {tolerance:.0%})")
    return regressions


def _prev_named_record(prefix):
    """Parsed payload of the newest ``{prefix}_rNN.json`` artifact — the
    generic trajectory lookup the MoE / long-context variants share."""
    best_round, best = -1, None
    here = os.path.dirname(os.path.abspath(__file__))
    for path in glob.glob(os.path.join(here, f"{prefix}_r*.json")):
        m = re.search(rf"{prefix}_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            parsed = rec.get("parsed") or rec
            val = parsed.get("value")
        except Exception:
            continue
        if val is not None and int(m.group(1)) > best_round:
            best_round, best = int(m.group(1)), parsed
    return best


def _next_named_round(here: str, prefix: str) -> int:
    rounds = [int(m.group(1)) for p in
              glob.glob(os.path.join(here, f"{prefix}_r*.json"))
              if (m := re.search(rf"{prefix}_r(\d+)\.json$", p))]
    return max(rounds, default=0) + 1


def _emit_named(args, result: dict, schema: str, prefix: str) -> None:
    if not args.emit:
        return
    here = os.path.dirname(os.path.abspath(__file__))
    path_out = args.emit
    if path_out == "auto":
        path_out = os.path.join(
            here, f"{prefix}_r{_next_named_round(here, prefix):02d}.json")
    with open(path_out, "w") as f:
        json.dump({"schema": schema, "parsed": result}, f, indent=1)
    print(f"wrote {path_out}", file=sys.stderr)


def _metric_series(name):
    from paddle_tpu.observability import default_registry
    m = default_registry().get(name)
    return {"/".join(k) or "all": c.value() for k, c in m.series()} \
        if m is not None else {}


def compare_moe_records(cur: dict, prev: dict, tolerance: float = 0.05):
    """MoE trajectory check: the base value/step-time/calibration clauses
    plus the grouped-kernel cost-model byte ratio (better-LOWER — the
    kernel's whole claim is that the [G, C, h] hidden intermediate never
    touches HBM) and knob-off parity, which must never rot."""
    regressions = compare_records(cur, prev, tolerance)
    pg = (prev.get("detail") or {}).get("grouped_kernel") or {}
    cg = (cur.get("detail") or {}).get("grouped_kernel") or {}
    pr, cr = pg.get("bytes_ratio"), cg.get("bytes_ratio")
    if pr and cr and float(cr) > float(pr) * (1.0 + tolerance):
        regressions.append(
            f"grouped_kernel.bytes_ratio {float(cr):.3f} > prev "
            f"{float(pr):.3f} + {tolerance:.0%} tolerance")
    cp = (cur.get("detail") or {}).get("knob_off_parity") or {}
    if cp and not cp.get("ok", True):
        regressions.append(
            f"knob_off_parity rel_diff {cp.get('rel_diff')} exceeded bar")
    return regressions


def compare_longctx_records(cur: dict, prev: dict,
                            tolerance: float = 0.05):
    """Long-context trajectory check: base clauses plus the ring-vs-
    single-device parity error, judged against an ABSOLUTE bar (the
    oracle is exact math, not a noisy timing, so drift is never ok)."""
    regressions = compare_records(cur, prev, tolerance)
    cp = (cur.get("detail") or {}).get("parity") or {}
    bar = cp.get("bar", 2e-5)
    ce = cp.get("max_abs_err")
    if ce is not None and float(ce) > float(bar):
        regressions.append(
            f"parity.max_abs_err {float(ce):.2e} > {float(bar):.0e} bar")
    return regressions


def _moe_bench(args):
    """MoE workload bench (ISSUE 18): full train step (fwd+bwd+AdamW) of
    a MoE decoder with the grouped expert-matmul Pallas kernel ON,
    emitting the ``moe_mfu`` trajectory line (activated-FLOPs MFU, the
    standard MoE accounting — idle experts do no math).

    The detail payload carries the acceptance evidence next to the
    headline: the cost-model HBM-byte ratio of the grouped kernel vs the
    dense-einsum dispatch at the sweep shape (< 0.5 means the [G, C, h]
    hidden intermediate never round-trips HBM), knob-off loss parity
    (``PADDLE_TPU_GROUPED_MOE=0`` must reproduce the reference
    numerics), and the per-trace implementation-path counters.  The
    measured step feeds the calibration ledger like the dense bench."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pp
    from paddle_tpu import analysis
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import MoEConfig, MoEForCausalLM
    from paddle_tpu.ops.pallas import autotune as at
    from paddle_tpu.ops.pallas import grouped_matmul as gm

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    mode = os.environ.get("PT_MOE_DISPATCH", "einsum")
    if on_tpu:
        # DeepSeekMoE-family dims scaled to one 16G chip (the
        # moe_train_bench "large" config, grouped-kernel path on)
        cfg = MoEConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            moe_intermediate_size=1408, num_hidden_layers=4,
            num_attention_heads=16, num_key_value_heads=16,
            num_experts=16, num_experts_per_tok=2, num_shared_experts=1,
            first_k_dense_replace=1, max_position_embeddings=2048,
            capacity_factor=1.25, dispatch_mode=mode, dtype="bfloat16")
        batch, seq, iters, warmup = 4, 2048, 8, 2
    else:  # CI/CPU smoke — interpret-mode pallas
        cfg = MoEConfig.tiny(dispatch_mode=mode)
        batch, seq, iters, warmup = 2, 64, 2, 1
    batch = int(os.environ.get("PT_MOE_BATCH", batch))

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    batch_dict = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    def build_step(grouped: bool):
        os.environ["PADDLE_TPU_GROUPED_MOE"] = "1" if grouped else "0"
        pp.seed(0)
        model = MoEForCausalLM(cfg)
        opt = pp.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)
        return TrainStep(model, opt)

    knob_prev = os.environ.get("PADDLE_TPU_GROUPED_MOE")
    try:
        # knob-off reference first: same seed, same batch, one step —
        # the grouped path must reproduce this loss
        step_off = build_step(False)
        loss_off = float(step_off(batch_dict))
        del step_off

        step = build_step(True)
        loss_on = float(step(batch_dict))  # warmup step 1 + parity probe
        for _ in range(warmup - 1):
            step(batch_dict)
        jax.block_until_ready(step.params)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(batch_dict)
        jax.block_until_ready(step.params)
        dt = (time.perf_counter() - t0) / iters
    finally:
        if knob_prev is None:
            os.environ.pop("PADDLE_TPU_GROUPED_MOE", None)
        else:
            os.environ["PADDLE_TPU_GROUPED_MOE"] = knob_prev

    rel_diff = abs(loss_on - loss_off) / max(abs(loss_off), 1e-9)
    parity_ok = rel_diff <= 5e-3

    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())
    expert_params = sum(int(np.prod(a.shape))
                        for name, a in step.params.items()
                        if ".experts." in name)
    idle = int(expert_params
               * (cfg.num_experts - cfg.num_experts_per_tok)
               / cfg.num_experts)
    activated = n_params - idle
    tokens = batch * seq
    flops_per_token = 6 * activated + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    mfu = flops_per_token * tokens / dt / _peak_flops(dev)

    # grouped-kernel acceptance: cost-model HBM bytes at the sweep shape
    # vs the dense-einsum pair — trace-level analysis, no execution
    g, c, d, h, dtp = at.SWEEP_SHAPES["grouped_matmul"][0]
    jdt = jnp.bfloat16 if dtp == "bfloat16" else jnp.float32
    xs = [jnp.zeros(s, jdt) for s in
          ((g, c, d), (g, d, h), (g, h), (g, h, d), (g, d))]

    def _cost(fn):
        rep = analysis.check(fn, *xs, passes=["cost-model"])
        return rep.extras["cost"]

    cgr = _cost(lambda *a: gm.grouped_expert_ffn(*a))
    cdn = _cost(lambda *a: gm.grouped_expert_ffn_reference(*a))
    bytes_ratio = cgr.total_bytes / max(cdn.total_bytes, 1)

    # calibration-ledger feed: the measured MoE step lands in the
    # corpus with its roofline prediction, same as the dense bench
    from paddle_tpu.observability import calibration
    if calibration.enabled():
        from paddle_tpu.observability.device_profiler import \
            detect_roofline
        peak_r, _bw = detect_roofline()
        pred_s = flops_per_token * tokens / peak_r if peak_r else 0.0
        calibration.ledger().record(
            "moe_step", (batch, seq), measured_s=dt,
            predicted_s=pred_s, provenance="bench")
    calibration_detail = calibration.bench_detail()

    prev = _prev_named_record("BENCH_moe")
    result = {
        "metric": "moe_mfu",
        "value": round(mfu, 8),  # CPU smoke values are ~1e-6 of peak
        "unit": "fraction_of_peak_activated_flops",
        "vs_prev": round(mfu / float(prev["value"]), 4)
        if prev and prev.get("value") else None,
        "detail": {
            "tokens_per_sec_per_chip": round(tokens / dt, 1),
            "step_time_s": round(dt, 4),
            "params_total": n_params,
            "params_activated": activated,
            "dispatch_mode": mode,
            "experts": cfg.num_experts,
            "top_k": cfg.num_experts_per_tok,
            "batch": batch, "seq": seq,
            "device": getattr(dev, "device_kind", dev.platform),
            "final_loss": float(loss),
            "grouped_kernel": {
                "enabled": True,
                "bytes": int(cgr.total_bytes),
                "dense_bytes": int(cdn.total_bytes),
                "bytes_ratio": round(float(bytes_ratio), 4),
                "shape": {"g": g, "c": c, "d": d, "h": h, "dtype": dtp},
                "paths": _metric_series(
                    "paddle_tpu_grouped_moe_path_total"),
            },
            "knob_off_parity": {
                "loss_grouped": loss_on,
                "loss_reference": loss_off,
                "rel_diff": float(rel_diff),
                "ok": bool(parity_ok),
            },
            "calibration": calibration_detail,
        },
    }
    print(json.dumps(result))
    _emit_named(args, result, "bench_moe", "BENCH_moe")

    rc = 0
    if args.compare:
        if prev is None:
            print(json.dumps({"bench_compare": {
                "ok": True, "note": "no previous BENCH_moe artifact"}}),
                file=sys.stderr)
        else:
            tol = 0.05 if args.tolerance is None else args.tolerance
            regressions = compare_moe_records(result, prev, tol)
            print(json.dumps({"bench_compare": {
                "ok": not regressions, "tolerance": tol,
                "prev_value": prev.get("value"),
                "regressions": regressions}}), file=sys.stderr)
            rc = 1 if regressions else rc
    if bytes_ratio >= 0.5:
        print(f"moe bench: grouped-kernel bytes ratio "
              f"{bytes_ratio:.3f} >= 0.5x dense acceptance bar",
              file=sys.stderr)
        rc = 1
    if not parity_ok:
        print(f"moe bench: knob-off parity FAILED "
              f"(rel_diff {rel_diff:.2e})", file=sys.stderr)
        rc = 1
    return rc


def _longctx_bench(args):
    """Long-context bench (ISSUE 18): flash-backed ring attention on an
    ``sp`` mesh, emitting the ``longctx_mfu`` trajectory line (attention
    FLOPs utilisation of the fwd+bwd step at O(seq/sp) per-device
    memory).  Off-TPU the mesh is the 8-way virtual CPU host platform —
    the same program the multichip dryrun compiles — with pallas in
    interpret mode.  The detail payload carries the single-device flash
    parity error (absolute bar: the oracle is exact math), the striped
    causal-balance variant's parity, and the per-device memory story;
    the measured step feeds the calibration ledger."""
    if "tpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
        from _jax_platform import force_cpu_default
        force_cpu_default(min_devices=8)
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import paddle_tpu.distributed as dist
    from paddle_tpu.nn.functional.attention import _sdpa_reference

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    sp = int(os.environ.get("PT_LONGCTX_SP", "4"))
    if on_tpu:
        b, s, h, d = 1, 32768, 8, 128
        iters, warmup = 5, 2
    else:  # CI/CPU smoke — interpret-mode flash per hop
        b, s, h, d = 1, 512, 4, 32
        iters, warmup = 2, 1
    s = int(os.environ.get("PT_LONGCTX_SEQ", s))
    sp = min(sp, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (b, s, h, d), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (b, s, h, d), jnp.float32) * 0.5

    ring = dist.make_ring_attention(mesh, causal=True, impl="flash")
    out = jax.jit(ring)(q, k, v)
    want = _sdpa_reference(q, k, v, is_causal=True)
    max_err = float(jnp.max(jnp.abs(out - want)))
    parity_bar = 2e-5  # fp32 operands
    parity_ok = max_err <= parity_bar

    # striped causal-balance variant: operands pre-striped rank-major,
    # unstriped output must match the same oracle
    def _stripe(x):
        return jnp.concatenate([x[:, r::sp] for r in range(sp)], axis=1)

    def _unstripe(y):
        t = y.reshape(b, sp, s // sp, *y.shape[2:])
        return jnp.swapaxes(t, 1, 2).reshape(y.shape)

    striped = dist.make_striped_ring_attention(mesh, causal=True)
    out_s = _unstripe(jax.jit(striped)(_stripe(q), _stripe(k), _stripe(v)))
    striped_err = float(jnp.max(jnp.abs(out_s - want)))

    # timed: fwd+bwd through the flash-hop custom VJP — the training
    # cost the MFU headline measures
    loss_fn = jax.jit(jax.value_and_grad(
        lambda q, k, v: (ring(q, k, v) ** 2).mean(), argnums=(0, 1, 2)))
    for _ in range(warmup):
        loss_fn(q, k, v)
    jax.block_until_ready(loss_fn(q, k, v)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        val, grads = loss_fn(q, k, v)
    jax.block_until_ready(grads[0])
    dt = (time.perf_counter() - t0) / iters

    # attention FLOPs: fwd = 4*b*h*s^2*d (QK^T + PV), bwd = 2x fwd
    # (dQ/dK/dV + recompute), halved for causal
    flops = 12 * b * h * s * s * d * 0.5
    mfu = flops / dt / _peak_flops(dev)

    from paddle_tpu.distributed.sharding import overlap_enabled
    from paddle_tpu.observability import calibration
    if calibration.enabled():
        from paddle_tpu.observability.device_profiler import \
            detect_roofline
        peak_r, _bw = detect_roofline()
        calibration.ledger().record(
            "longctx_step", (b, s, sp), measured_s=dt,
            predicted_s=flops / peak_r if peak_r else 0.0,
            provenance="bench")
    calibration_detail = calibration.bench_detail()

    # per-device memory story: resident kv vs the dense score matrix
    kv_bytes_per_dev = 2 * b * (s // sp) * h * d * 4
    dense_scores_bytes = b * h * s * s * 4

    prev = _prev_named_record("BENCH_longctx")
    result = {
        "metric": "longctx_mfu",
        "value": round(mfu, 8),  # CPU smoke values are ~1e-6 of peak
        "unit": "fraction_of_peak",
        "vs_prev": round(mfu / float(prev["value"]), 4)
        if prev and prev.get("value") else None,
        "detail": {
            "tokens_per_sec": round(b * s / dt, 1),
            "step_time_s": round(dt, 4),
            "batch": b, "seq": s, "heads": h, "head_dim": d,
            "sp": sp, "impl": "flash", "causal": True,
            "seq_per_device": s // sp,
            "kv_bytes_per_device": kv_bytes_per_dev,
            "dense_scores_bytes": dense_scores_bytes,
            "collective_overlap": bool(overlap_enabled()),
            "device": getattr(dev, "device_kind", dev.platform),
            "final_loss": float(val),
            "parity": {
                "max_abs_err": max_err,
                "striped_max_abs_err": striped_err,
                "bar": parity_bar,
                "ok": bool(parity_ok),
            },
            "calibration": calibration_detail,
        },
    }
    print(json.dumps(result))
    _emit_named(args, result, "bench_longctx", "BENCH_longctx")

    rc = 0
    if args.compare:
        if prev is None:
            print(json.dumps({"bench_compare": {
                "ok": True,
                "note": "no previous BENCH_longctx artifact"}}),
                file=sys.stderr)
        else:
            tol = 0.05 if args.tolerance is None else args.tolerance
            regressions = compare_longctx_records(result, prev, tol)
            print(json.dumps({"bench_compare": {
                "ok": not regressions, "tolerance": tol,
                "prev_value": prev.get("value"),
                "regressions": regressions}}), file=sys.stderr)
            rc = 1 if regressions else rc
    if not parity_ok:
        print(f"longctx bench: ring-vs-flash parity FAILED "
              f"(max_abs_err {max_err:.2e} > {parity_bar:.0e})",
              file=sys.stderr)
        rc = 1
    return rc


def _recovery_drill(args):
    """MTTR drill (ISSUE 14): kill a training rank mid-run under the
    chaos registry, recover it twice — from a peer's in-memory snapshot
    and from the disk checkpoint — in the same artifact, and prove the
    post-recovery loss trajectory is bitwise identical to the
    uninterrupted run.  Both paths resume on a pre-warmed step (the
    relaunch/compile cost is common and measured by the cold-start
    artifact), so ``mttr_s`` isolates the restore path itself:
    detect -> state restored -> first resumed step retired."""
    import jax

    import paddle_tpu as pp
    from paddle_tpu import robustness
    from paddle_tpu.distributed.checkpoint import AutoCheckpoint
    from paddle_tpu.distributed.elastic import free_port
    from paddle_tpu.distributed.tcp_store import TCPStore
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.robustness import recovery as rec

    drill_t0 = time.perf_counter()
    # big enough that restore cost is real (tens of MB of state), small
    # enough for a CI box
    cfg = LlamaConfig.tiny(vocab_size=512, hidden_size=256,
                           intermediate_size=512, num_hidden_layers=4)
    # kill late enough that the disk side holds its full keep=3
    # candidate set — restore_latest digest-validates every candidate,
    # which is the real production restore cost
    steps_total, kill_step, snap_interval = 15, 10, 3
    bsz, seq = 2, 64

    def batch_for(i):
        r = np.random.default_rng(1000 + i)
        ids = r.integers(0, cfg.vocab_size, (bsz, seq + 1))
        return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    def build_step():
        pp.seed(0)
        model = LlamaForCausalLM(cfg)
        opt = pp.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
        return TrainStep(model, opt)

    import tempfile
    ckpt_dir = tempfile.mkdtemp(prefix="pt_recovery_drill_")
    store = TCPStore("127.0.0.1", free_port(), is_master=True)
    snap = rec.PeerSnapshotter(store, rank=0, world_size=2,
                               interval_steps=snap_interval)
    ckpt = AutoCheckpoint(ckpt_dir, keep=3,
                          save_interval_steps=snap_interval)

    # the kill rides the chaos registry like every other drill: the
    # spec's nth counts loop iterations, so the fault fires AT kill_step
    robustness.inject("recovery.rank_kill", nth=kill_step, times=1)

    # reference run: doubles as the victim's timeline — snapshots and
    # checkpoints stop at the kill (a dead rank ships nothing), but the
    # loop runs to the end to record the uninterrupted loss trajectory
    # the recovered run must bitwise-match
    victim = build_step()
    losses_ref = {}
    killed_at = None
    pending = None
    for i in range(1, steps_total + 1):
        loss = victim(batch_for(i))
        losses_ref[i] = np.asarray(loss).tobytes()
        if killed_at is None:
            state = victim.state_dict()
            snap.maybe_snapshot(i, state)
            pending = ckpt.maybe_save(
                i, rec.flatten_for_checkpoint(state)) or pending
        if killed_at is None and robustness.fault_fires(
                "recovery.rank_kill", step=i):
            killed_at = i
    assert killed_at == kill_step, "chaos kill did not fire"
    if pending is not None:
        pending.wait()   # the step-6 disk save must be durable; the
        # async-save-racing-a-kill hazard has its own chaos test

    # the replacement rank: pre-built and pre-warmed (one throwaway
    # step compiles the executable), then restored into — twice
    template = build_step()
    jax.block_until_ready(template(batch_for(1)))

    # MTTR here = detect -> restored state INSTALLED on device (the
    # rank can train again); the first resumed step is ordinary
    # training cost, identical on both paths, timed separately.  Each
    # path runs 3x (min) — standard practice for sub-second timings on
    # a shared host.

    def drop_page_cache(path):
        # a replacement rank boots with a COLD page cache — warm
        # re-reads of files this very process just wrote would flatter
        # the disk path (fsync first: fadvise only drops clean pages)
        for root, _dirs, files in os.walk(path):
            for f in files:
                try:
                    fd = os.open(os.path.join(root, f), os.O_RDONLY)
                    os.fsync(fd)
                    os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
                    os.close(fd)
                except OSError:
                    pass

    # disk-restore path: newest VALID checkpoint (digest-validated walk
    # over every candidate step dir — the real production restore cost)
    disk_restore_w, mttr_disk_w = [], []
    for _ in range(3):
        drop_page_cache(ckpt_dir)
        t0 = time.perf_counter()
        step_d, flat_d = ckpt.restore_latest()
        state_d = rec.unflatten_from_checkpoint(flat_d)
        disk_restore_w.append(time.perf_counter() - t0)
        template.set_state_dict(state_d)
        jax.block_until_ready(template.params)
        mttr_disk_w.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    jax.block_until_ready(template(batch_for(step_d + 1)))
    resume_step_disk_s = time.perf_counter() - t0
    disk_restore_s, mttr_disk = min(disk_restore_w), min(mttr_disk_w)

    # peer-restore path: RAM fetch from the ring buddy's mailbox —
    # resident by construction, which is the point of peer replication
    peer_restore_w, mttr_peer_w = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        step_p, state_p, path = rec.resume_train_state(
            store, rank=0, auto_ckpt=ckpt)
        peer_restore_w.append(time.perf_counter() - t0)
        template.set_state_dict(state_p)
        jax.block_until_ready(template.params)
        mttr_peer_w.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    loss = template(batch_for(step_p + 1))
    jax.block_until_ready(template.params)
    resume_step_s = time.perf_counter() - t0
    peer_restore_s, mttr_peer = min(peer_restore_w), min(mttr_peer_w)
    staleness = killed_at - step_p

    # post-recovery trajectory: bitwise vs the uninterrupted run
    losses_rec = {step_p + 1: np.asarray(loss).tobytes()}
    for i in range(step_p + 2, steps_total + 1):
        losses_rec[i] = np.asarray(template(batch_for(i))).tobytes()
    bitwise = all(losses_rec[i] == losses_ref[i]
                  for i in range(step_p + 1, steps_total + 1))

    # SDC sentinel drill: three simulated DP replicas digest the same
    # params; an armed bit-flip corrupts replica 1's view — it must be
    # detected, blamed via deterministic replay, and quarantined
    true_params = template.params
    sentinels = [rec.SDCSentinel(store, rank=r, dp_peers=[0, 1, 2],
                                 host=f"drill-h{r}", timeout=1.0)
                 for r in range(3)]
    sentinels[0].publish(100, true_params)
    robustness.inject("train.sdc_flip", times=1)
    sentinels[1].publish(100, true_params)
    robustness.clear_faults("train.sdc_flip")
    sentinels[2].publish(100, true_params)
    verdict = sentinels[0].verify(
        100, replay=lambda: rec.params_digest(true_params))
    sdc = {
        "detected": not verdict["ok"],
        "blamed": verdict["blamed"],
        "blamed_correct": verdict["blamed"] == [1],
        "replay_confirmed": verdict["replayed"],
        "quarantined": verdict["quarantined"],
    }
    robustness.clear_faults("recovery.rank_kill")

    from paddle_tpu.observability import goodput as _goodput
    ledger = _goodput.compute_goodput(
        wall_s=time.perf_counter() - drill_t0)
    store.close()
    import shutil
    shutil.rmtree(ckpt_dir, ignore_errors=True)

    n_params = sum(int(np.prod(a.shape))
                   for a in template.params.values())
    speedup = mttr_disk / mttr_peer if mttr_peer > 0 else float("inf")
    result = {
        "metric": "recovery_restore_speedup",
        "value": round(speedup, 2),
        "unit": "x_vs_disk_restore",
        "vs_baseline": round(speedup / 3.0, 4),   # acceptance bar: 3x
        "detail": {"recovery": {
            "mttr_s": round(mttr_peer, 4),
            "mttr_disk_s": round(mttr_disk, 4),
            "restore_path": path,
            "restore_s": round(peer_restore_s, 4),
            "disk_restore_s": round(disk_restore_s, 4),
            "resume_step_s": round(resume_step_s, 4),
            "resume_step_disk_s": round(resume_step_disk_s, 4),
            "snapshot_staleness_steps": staleness,
            "snapshot_interval_steps": snap_interval,
            "snapshot_bytes": int(snap._metrics["snapshot_bytes"]
                                  .value()),
            "kill_step": killed_at,
            "restored_step": step_p,
            "steps": steps_total,
            "replayed_steps": steps_total - step_p,
            "trajectory_bitwise_match": bool(bitwise),
            "goodput": {
                "value": round(ledger["goodput"], 4),
                "productive_s": round(ledger["productive_s"], 4),
                "wall_s": round(ledger["wall_s"], 4),
            },
            "sdc": sdc,
            "params": n_params,
        }},
    }
    print(json.dumps(result))

    if args.emit:
        here = os.path.dirname(os.path.abspath(__file__))
        path_out = args.emit
        if path_out == "auto":
            path_out = os.path.join(
                here,
                f"BENCH_recovery_r{_next_recovery_round(here):02d}.json")
        with open(path_out, "w") as f:
            json.dump({"schema": "bench_recovery", "parsed": result}, f,
                      indent=1)
        print(f"wrote {path_out}", file=sys.stderr)

    rc = 0
    if args.compare:
        prev = _prev_recovery_record()
        if prev is None:
            print(json.dumps({"bench_compare": {
                "ok": True, "note": "no previous BENCH_recovery "
                                    "artifact"}}), file=sys.stderr)
        else:
            # restore timing on a shared CI host is noisy — the default
            # recovery tolerance is wide; the hard floors below still
            # gate correctness absolutely
            tol = 0.5 if args.tolerance is None else args.tolerance
            regressions = compare_records(result, prev, tol)
            print(json.dumps({"bench_compare": {
                "ok": not regressions, "tolerance": tol,
                "prev_value": prev.get("value"),
                "regressions": regressions}}), file=sys.stderr)
            rc = 1 if regressions else rc
    if not bitwise:
        print("recovery drill: post-recovery trajectory DIVERGED from "
              "the uninterrupted run", file=sys.stderr)
        rc = 1
    if not (sdc["detected"] and sdc["blamed_correct"]):
        print("recovery drill: SDC bit-flip not detected/blamed "
              f"correctly ({sdc})", file=sys.stderr)
        rc = 1
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--compare", action="store_true",
                    help="flag regressions vs the newest BENCH_r*.json "
                         "(exit 1 beyond --tolerance)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="relative regression tolerance for --compare "
                         "(default 0.05; 0.25 for --compare-serve)")
    ap.add_argument("--no-device-profile", action="store_true",
                    help="skip the roofline-gap segment profiling pass")
    ap.add_argument("--compare-serve", metavar="RESULT_JSON",
                    help="instead of running the training bench, "
                         "regression-check a bench_serve.py result file "
                         "against the newest BENCH_serve_r*.json "
                         "(TTFT/TPOT p99 + tokens/s, exit 1 beyond "
                         "--tolerance)")
    ap.add_argument("--recovery-drill", action="store_true",
                    help="instead of the training bench, run the MTTR "
                         "drill: chaos-kill a rank mid-run, recover "
                         "from a peer in-memory snapshot AND the disk "
                         "checkpoint, verify the bitwise loss "
                         "trajectory + SDC sentinel blame (exit 1 on "
                         "any failure)")
    ap.add_argument("--moe", action="store_true",
                    help="instead of the dense training bench, run the "
                         "MoE workload bench (grouped expert-matmul "
                         "kernel on) and emit the moe_mfu line; "
                         "--compare checks the newest BENCH_moe_r*.json")
    ap.add_argument("--longctx", action="store_true",
                    help="instead of the dense training bench, run the "
                         "long-context ring-attention bench and emit "
                         "the longctx_mfu line; --compare checks the "
                         "newest BENCH_longctx_r*.json")
    ap.add_argument("--emit", metavar="PATH", nargs="?", const="auto",
                    help="with --recovery-drill/--moe/--longctx: write "
                         "the artifact (auto = next "
                         "BENCH_{recovery,moe,longctx}_rNN.json beside "
                         "this script)")
    args = ap.parse_args(argv)

    if args.recovery_drill:
        return _recovery_drill(args)
    if args.moe:
        return _moe_bench(args)
    if args.longctx:
        return _longctx_bench(args)

    if args.compare_serve:
        with open(args.compare_serve) as f:
            rec = json.load(f)
        cur = rec.get("parsed") or rec
        prev = _prev_serve_record()
        if prev is None:
            print(json.dumps({"bench_compare": {
                "ok": True, "note": "no previous BENCH_serve artifact"}}),
                file=sys.stderr)
            return 0
        tol = 0.25 if args.tolerance is None else args.tolerance
        regressions = compare_serve_records(cur, prev, tol)
        print(json.dumps({"bench_compare": {
            "ok": not regressions, "tolerance": tol,
            "prev_value": prev.get("value"),
            "regressions": regressions}}), file=sys.stderr)
        return 1 if regressions else 0

    import jax

    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    bench_t0 = time.perf_counter()
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # Llama-3-8B-proportioned, scaled to fit one 16G-HBM chip with the
        # full AdamW training state (bf16 params + f32 master + f32 m/v
        # ≈ 14 bytes/param → ~810M params ≈ 11.3G + activations)
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8, max_position_embeddings=4096,
            rope_theta=500000.0, dtype="bfloat16")
        # measured on v5e (this model): b4/s2048/no-remat + fused
        # chunked lm-head CE = 0.52 MFU —
        # the shipped default (longest pretraining context that fits with
        # full AdamW state).  Sweep: full remat 0.39 (recompute tax);
        # b5 0.49 (non-pow2 tiling); b2/s4096 0.42; b8/s1024 0.58 (short
        # context inflates MFU — not representative); b8/s2048 OOM even
        # with dots-saveable remat.
        batch, seq, iters, warmup = 4, 2048, 10, 3
    else:  # CI/CPU smoke
        cfg = LlamaConfig.tiny()
        batch, seq, iters, warmup = 4, 64, 3, 1
    batch = int(os.environ.get("PT_BENCH_BATCH", batch))
    seq = int(os.environ.get("PT_BENCH_SEQ", seq))
    remat = os.environ.get("PT_BENCH_REMAT", "0") == "1"
    remat_policy = os.environ.get("PT_BENCH_REMAT_POLICY") or None
    accum = int(os.environ.get("PT_BENCH_ACCUM", "1"))
    profile_segments = not args.no_device_profile and \
        os.environ.get("PT_BENCH_PROFILE", "1") != "0"

    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt, remat=on_tpu and remat,
                     remat_policy=remat_policy, accum_steps=accum)

    n_params = sum(int(np.prod(a.shape)) for a in step.params.values())
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    batch_dict = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    # explicit AOT compile first: the measured run dispatches through the
    # compiled executable (no first-step compile spike inside timing) and
    # lower/compile wall time + XLA's flops/bytes/peak-memory become part
    # of the artifact.  With PADDLE_TPU_COMPILE_CACHE=1 this consults the
    # persistent executable cache — a warm cache turns trace+compile into
    # a deserialize-and-load, which is the cold-start story the
    # `cold_start` detail section below records.
    compile_info = step.compile(batch_dict)

    # device prefetch: H2D for batch N+1 rides behind step N instead of
    # serializing ahead of it (paddle_tpu.io.device_prefetch)
    from paddle_tpu.io import device_prefetch

    def batches(n):
        for _ in range(n):
            yield batch_dict

    first_step_s = None
    for b in device_prefetch(batches(warmup), depth=2):
        t0 = time.perf_counter()
        step(b)
        if first_step_s is None:
            import jax as _jax
            _jax.block_until_ready(step.params)
            first_step_s = time.perf_counter() - t0
    jax.block_until_ready(step.params)
    # min-of-windows timing: the tunneled chip shows run-to-run noise
    # (observed 0.50-0.514 MFU for the identical executable); the fastest
    # window is the true program speed, standard benchmarking practice
    windows = []
    for _ in range(3):
        prefetched = device_prefetch(batches(iters), depth=2)
        next_batches = iter(prefetched)
        first = next(next_batches)  # H2D outside the timed window
        t0 = time.perf_counter()
        loss = step(first)
        for b in next_batches:
            loss = step(b)
        jax.block_until_ready(step.params)
        windows.append((time.perf_counter() - t0) / iters)
        prefetched.close()
    dt = min(windows)  # headline; mean reported alongside in detail

    tokens = batch * seq
    # fwd+bwd FLOPs: 6N per token + attention 12*L*s*d per token
    flops_per_token = 6 * n_params + \
        12 * cfg.num_hidden_layers * seq * cfg.hidden_size
    mfu = flops_per_token * tokens / dt / _peak_flops(dev)
    tok_per_sec = tokens / dt

    # kernel-path attribution: which implementations this run compiled,
    # so BENCH_r*.json trajectories can attribute wins to paths
    from paddle_tpu.observability import default_registry
    from paddle_tpu.distributed.sharding import overlap_enabled
    from paddle_tpu.ops.pallas.cross_entropy import fused_ce_enabled
    from paddle_tpu.ops.pallas.flash_attention import flash_bwd_env
    from paddle_tpu.ops.pallas.fused_block import (fused_block_enabled,
                                                   fused_block_tier)

    def _series(name):
        m = default_registry().get(name)
        return {"/".join(k) or "all": c.value() for k, c in m.series()} \
            if m is not None else {}

    pb = flash_bwd_env()
    paths = {
        "fused_ce_enabled": bool(fused_ce_enabled()),
        "fused_ce_calls": _series("paddle_tpu_fused_ce_calls_total"),
        "flash_bwd": "pallas" if pb else ("blockwise" if pb is not None
                                         else "blockwise(default)"),
        "flash_bwd_traces": _series("paddle_tpu_flash_bwd_path_total"),
        # which block segments this run compiled fused vs reference, and
        # whether tuned block sizes came from the persistent cache —
        # BENCH trajectories can attribute wins to the exact code path
        "fused_block_enabled": bool(fused_block_enabled()),
        "fused_block_tier": fused_block_tier(),
        "fused_block_traces": _series("paddle_tpu_fused_block_path_total"),
        "autotune_cache": _series("paddle_tpu_autotune_cache_total"),
        # compute/collective overlap (ISSUE 15): whether the knob was on
        # and which paths actually traced overlap-expressed collectives
        "collective_overlap": bool(overlap_enabled()),
        "overlap_traces": _series("paddle_tpu_collective_overlap_total"),
        "accum_steps": accum,
        "device_prefetch": True,
    }

    # device-time breakdown: where the step's MFU gap actually sits —
    # the ranked attribution rows are the fusion target list (ROADMAP 2)
    from paddle_tpu.observability.device_profiler import (
        DeviceProfiler, device_memory_monitor, llama_step_segments)
    device_profile = None
    if profile_segments:
        try:
            prof = DeviceProfiler()
            for seg in llama_step_segments(model, batch_dict):
                prof.add(seg)
            result = prof.profile(reps=2, warmup=1,
                                  parent_span="train.step")
            device_profile = {
                "segments": result.to_dicts(top=8),
                "peak_flops": result.peak_flops,
                "hbm_bw": result.hbm_bw,
            }
        except Exception as e:   # attribution must never sink the bench
            device_profile = {"error": f"{type(e).__name__}: {e}"}
    live_watermark = device_memory_monitor().watermark

    # cold-start ledger (ROADMAP 5): how long from process start to a
    # runnable step — trace, compile-or-load (cache hit → deserialize
    # time), first real step — plus the compile-cache counters that say
    # WHICH path this run took.  --compare guards it once two artifacts
    # carry the section.
    from paddle_tpu import compile_cache
    cache_series = _series("paddle_tpu_compile_cache_total")
    cold_start = {
        "trace_s": round(compile_info.lower_s, 4),
        "compile_or_load_s": round(compile_info.compile_s, 4),
        "first_step_s": round(first_step_s or 0.0, 4),
        "total_s": round(compile_info.lower_s + compile_info.compile_s
                         + (first_step_s or 0.0), 4),
        "cache_hit": bool(compile_info.cached),
        "cache_enabled": compile_cache.enabled(),
        "cache": {
            "hit": sum(v for k, v in cache_series.items()
                       if k.endswith("/hit")),
            "miss": sum(v for k, v in cache_series.items()
                        if k.endswith("/miss")),
            "deserialize_error": sum(
                v for k, v in cache_series.items()
                if k.endswith("/deserialize_error")),
        },
    }

    # measurement ledger (ROADMAP 5): the whole measured train step
    # lands in the calibration corpus with its roofline prediction —
    # the record a fresh planner process calibrates against — and the
    # detail.calibration section summarizes residual health for
    # --compare (coverage better-higher, |residual| better-lower).
    # The profiler segments above already fed their own rows.
    from paddle_tpu.observability import calibration
    if calibration.enabled():
        peak = bw = None
        if profile_segments:
            try:
                peak, bw = prof.peak_flops, prof.hbm_bw
            except Exception:
                peak = bw = None
        if not peak or not bw:
            from paddle_tpu.observability.device_profiler import \
                detect_roofline
            peak, bw = detect_roofline()
        step_pred_s = max(
            compile_info.stats.flops / peak if peak else 0.0,
            compile_info.stats.bytes_accessed / bw if bw else 0.0)
        calibration.ledger().record(
            "train_step", (batch, seq), measured_s=dt,
            predicted_s=step_pred_s, provenance="bench")
    calibration_detail = calibration.bench_detail()

    # goodput ledger (fleet observability): productive step seconds over
    # the bench's own wall clock, with the lost-time attribution — the
    # field --compare guards alongside MFU once two artifacts carry it
    from paddle_tpu.observability import goodput as _goodput
    ledger = _goodput.compute_goodput(
        wall_s=time.perf_counter() - bench_t0)
    goodput_detail = {
        "value": round(ledger["goodput"], 4),
        "productive_s": round(ledger["productive_s"], 4),
        "wall_s": round(ledger["wall_s"], 4),
        "lost": {k: round(v, 4) for k, v in ledger["lost"].items()},
    }

    prev = _prev_value()
    result = {
        "metric": "llama_pretrain_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / 0.40, 4),
        "vs_prev": round(mfu / prev, 4) if prev else None,
        "detail": {
            "tokens_per_sec_per_chip": round(tok_per_sec, 1),
            "step_time_s": round(dt, 4),
            "step_time_mean_s": round(sum(windows) / len(windows), 4),
            "params": n_params,
            "batch": batch, "seq": seq,
            "device": getattr(dev, "device_kind", dev.platform),
            "final_loss": float(loss),
            "paths": paths,
            "compile": {
                "lower_s": round(compile_info.lower_s, 4),
                "compile_s": round(compile_info.compile_s, 4),
                "flops": compile_info.stats.flops,
                "bytes_accessed": compile_info.stats.bytes_accessed,
                "peak_hbm_bytes": compile_info.stats.peak_bytes,
            },
            "peak_hbm_bytes": compile_info.stats.peak_bytes,
            "device_live_bytes_watermark": live_watermark,
            "device_profile": device_profile,
            "cold_start": cold_start,
            "goodput": goodput_detail,
            "calibration": calibration_detail,
        },
    }
    print(json.dumps(result))

    if args.compare:
        prev_rec = _prev_record()
        if prev_rec is None:
            print(json.dumps({"bench_compare": {
                "ok": True, "note": "no previous BENCH artifact"}}),
                file=sys.stderr)
            return 0
        tol = 0.05 if args.tolerance is None else args.tolerance
        regressions = compare_records(result, prev_rec, tol)
        print(json.dumps({"bench_compare": {
            "ok": not regressions,
            "tolerance": tol,
            "prev_value": prev_rec.get("value"),
            "regressions": regressions}}), file=sys.stderr)
        if regressions:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
