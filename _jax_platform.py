"""Force the virtual CPU platform as jax's default backend.

Single home of the platform-forcing recipe, shared by tests/conftest.py and
__graft_entry__.py (the two entry points the driver/test-runner actually
invokes).  The environment's sitecustomize (PYTHONPATH /root/.axon_site)
force-sets ``jax.config.update("jax_platforms", "axon,cpu")`` in every
python process, which the ``JAX_PLATFORMS`` env var alone does NOT override;
any eager op would then dispatch to the tunneled remote TPU.  "cpu,axon"
keeps the tunnel visible (real-hardware smoke tests, single-chip bench) but
makes the virtual CPU mesh the default backend.
"""

from __future__ import annotations

import os
import re


def set_env(min_devices: int = 8) -> None:
    """Set JAX_PLATFORMS / XLA_FLAGS env vars (effective only before the
    first backend initialization in this process)."""
    os.environ["JAX_PLATFORMS"] = "cpu,axon"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={min_devices}"
        ).strip()
    elif int(m.group(1)) < min_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={min_devices}")


def force_cpu_default(min_devices: int = 1) -> None:
    """Make the virtual CPU platform the default backend, loudly.

    Handles three progressively worse situations:
    1. fresh process — env vars + config.update suffice;
    2. sitecustomize already ran config.update — our later update wins as
       long as backends are not yet initialized;
    3. backends already initialized on the TPU platform — tear them down
       (jax.extend.backend.clear_backends) and re-select.

    Raises RuntimeError if the default platform still isn't CPU, or if fewer
    than ``min_devices`` CPU devices exist (XLA parses
    --xla_force_host_platform_device_count only at first CPU-client
    creation, so an in-process fix is impossible at that point — the flag
    must be exported before the process starts).
    """
    set_env(max(min_devices, 8))
    import jax

    try:
        jax.config.update("jax_platforms", "cpu,axon")
        jax.devices()  # force platform init; raises if axon is unavailable
    except Exception:
        try:
            jax.config.update("jax_platforms", "cpu")
            jax.devices()
        except Exception:
            pass  # backends already initialized; recovered below
    if jax.devices()[0].platform != "cpu":
        # Backends were initialized on the TPU platform before we ran.
        # Tear them down and re-select; cheap in a fresh driver process
        # (no compile cache lost) and the only possible recovery.
        try:
            import jax.extend
            jax.extend.backend.clear_backends()
            jax.config.update("jax_platforms", "cpu,axon")
            jax.devices()
        except Exception:
            try:
                jax.config.update("jax_platforms", "cpu")
                jax.devices()
            except Exception:
                pass
    if jax.devices()[0].platform != "cpu":
        raise RuntimeError(
            "default jax platform is %r, not 'cpu' — a sitecustomize or "
            "driver override selected the TPU platform and backends could "
            "not be re-initialized; set JAX_PLATFORMS=cpu,axon before "
            "starting python" % jax.devices()[0].platform)
    n_cpu = len(jax.devices("cpu"))
    if n_cpu < min_devices:
        raise RuntimeError(
            f"only {n_cpu} CPU device(s) but {min_devices} are required; "
            f"XLA parses --xla_force_host_platform_device_count once, at "
            f"first CPU-client creation, so it must be in XLA_FLAGS before "
            f"this process starts (export XLA_FLAGS="
            f"--xla_force_host_platform_device_count={min_devices})")
