"""Serving benchmark: Poisson arrivals over the continuous-batching engine.

Serving joins the benchmark trajectory (training has BENCH_r*.json since
r02; serving had nothing).  Prints ONE JSON line:

    {"metric": "serving_tokens_per_s", "value", "unit", "detail": {...}}

with TTFT/TPOT p50/p99 under Poisson load, prefix-cache hit counters,
paged-block utilization, and speculative-decode accept counters in the
detail payload.  ``--emit`` writes a ``BENCH_serve_r*.json`` artifact so
``bench.py --compare-serve`` (or ``bench_serve.py --compare``) can guard
the trajectory the way training's ``--compare`` does.

The workload models the fleet case the paged KV cache exists for: every
request shares a system-prompt prefix (``--shared-prefix``) and appends
a short unique suffix, so with ``PADDLE_TPU_PAGED_KV=1`` the prefix
prefills once and later requests reuse its blocks (watch
``prefix_hit_tokens``).  ``--check-equivalence`` replays the workload
through the slot-contiguous engine and asserts token-for-token greedy
identity — the paged path must be a pure memory/scheduling optimization.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time

import numpy as np


def _percentiles(xs, ps=(50, 99)):
    if not xs:
        return {f"p{p}": None for p in ps}
    return {f"p{p}": float(np.percentile(xs, p)) for p in ps}


def _series(name):
    from paddle_tpu.observability import default_registry
    m = default_registry().get(name)
    return {"/".join(k) or "all": c.value() for k, c in m.series()} \
        if m is not None else {}


def _next_serve_round(here):
    rounds = [int(m.group(1)) for p in
              glob.glob(os.path.join(here, "BENCH_serve_r*.json"))
              if (m := re.search(r"BENCH_serve_r(\d+)\.json$", p))]
    return max(rounds, default=0) + 1


def _build_engine(model, args, paged, quant_weights="0", quant_kv="0"):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, slots=args.slots, max_len=args.max_len,
        prefill_buckets=(args.max_len // 2,),
        steps_per_sync=args.steps_per_sync if not args.spec else 1,
        paged_kv=paged,
        kv_block_size=args.block_size,
        prefill_chunk=args.chunk,
        spec_decode=args.spec if paged else 0,
        quant_weights=quant_weights, quant_kv=quant_kv)


def _build_router(model, args, quant_weights="0", quant_kv="0"):
    """The fleet under test: dedicated prefill replica(s) feeding a
    decode tier that runs DEEP step fusion (--decode-sync) — legal only
    because disaggregation means prefill never interleaves there.  The
    host-dispatch amortization is the measured fleet win; --fleet-mixed
    builds a homogeneous fleet instead (routing/spill only).
    --decode-slots sizes the decode tier's slot pool independently of
    the prefill tier (decode holds sequences for their whole decode
    phase; prefill slots turn over per prompt)."""
    from paddle_tpu.inference.router import ServingRouter
    ek = dict(slots=args.slots, max_len=args.max_len,
              prefill_buckets=(args.max_len // 2,),
              steps_per_sync=1, paged_kv=True,
              kv_block_size=args.block_size, prefill_chunk=args.chunk,
              quant_weights=quant_weights, quant_kv=quant_kv)
    dk = dict(steps_per_sync=args.decode_sync if not args.spec else 1,
              spec_decode=args.spec)
    if args.decode_slots:
        dk["slots"] = args.decode_slots
    prefill = 0 if args.fleet_mixed else max(1, args.prefill_replicas)
    return ServingRouter(
        model, replicas=args.fleet, prefill_replicas=prefill,
        engine_kwargs=ek, decode_kwargs=dk,
        warm_on_spawn=False)   # bench warms explicitly, outside timing


def _run_stats(eng, prompts, arrivals, args):
    """Drive one workload and fold the per-request timings."""
    results, rids, t0, t1 = _run_workload(eng, prompts, arrivals,
                                          args.max_new)
    ttfts, tpots, total_tokens = [], [], 0
    reused_tokens = 0.0
    accept_rates = []
    route_s, handoff_s = [], []
    timings = []
    for rid in rids:
        st = eng.request_status(rid)
        out = results.get(rid, [])
        total_tokens += len(out)
        t = st.timings if st is not None else {}
        timings.append(t)
        if t.get("ttft_s"):
            ttfts.append(t["ttft_s"])
        if t.get("decode_s") and len(out) > 1:
            tpots.append(t["decode_s"] / (len(out) - 1))
        reused_tokens += t.get("prefix_tokens_reused", 0.0)
        if t.get("route_s"):
            route_s.append(t["route_s"])
        if t.get("handoff_s"):
            handoff_s.append(t["handoff_s"])
        if args.spec:
            accept_rates.append(t.get("speculative_accept_rate", 0.0))
    wall = t1 - t0
    return {"results": results, "rids": rids, "wall": wall,
            "tokens": total_tokens,
            "tok_s": total_tokens / wall if wall > 0 else 0.0,
            "ttfts": ttfts, "tpots": tpots,
            "reused_tokens": reused_tokens,
            "accept_rates": accept_rates,
            "route_s": route_s, "handoff_s": handoff_s,
            "timings": timings}


def _workload(args, vocab):
    """(prompts, arrival_offsets): shared system prefix + per-request
    tails, Poisson inter-arrival gaps at --rps.

    ``--workload random`` (default): uniform-random unique suffixes —
    the adversarial case for speculative decoding (history n-grams
    predict nothing; accept rate ~0 at short horizons).
    ``--workload text``: repeated-phrase tails modeling natural-language
    redundancy (boilerplate, extraction, code) — the n-gram proposer's
    home turf, so ``--spec`` shows a non-zero accept rate the artifact
    records."""
    rng = np.random.default_rng(args.seed)
    shared = rng.integers(0, vocab, (args.shared_prefix,))
    prompts = []
    for _ in range(args.requests):
        if args.workload == "text":
            phrase = rng.integers(0, vocab,
                                  (int(rng.integers(4, 9)),))
            reps = max(2, -(-args.suffix_max // len(phrase)))
            tail = np.tile(phrase, reps)[:max(args.suffix_max, 8)]
        else:
            tail = rng.integers(0, vocab,
                                (int(rng.integers(2,
                                                  args.suffix_max + 1)),))
        prompts.append(np.concatenate([shared, tail]).astype(np.int32))
    gaps = rng.exponential(1.0 / args.rps, size=args.requests)
    arrivals = np.cumsum(gaps)
    arrivals[0] = 0.0
    return prompts, arrivals


def _session_drill(model, args, vocab, qw_mode="0", qkv_mode="0"):
    """Session-survivability drill (ISSUE 19): far more live sessions
    than the HBM pool holds, parked through the KV tier manager (host
    RAM + peer store) and resumed token-identically.

    A deliberately tiny paged pool (sized for ``slots`` concurrent
    sessions) serves ``--sessions`` logical sessions: each decodes a
    couple of tokens, parks (KV spilled to the tier), and later
    resumes (KV promoted back into fresh blocks).  The
    ``sessions_resident`` trajectory counts parked+active sessions
    after each park; its peak over the pool's HBM-equivalent session
    capacity is the survivability headline
    (``sessions_resident_ratio``).  A no-parking reference engine
    proves every resumed session's greedy tokens are identical, and
    one extra session resumes through an injected ``kv_tier.fetch``
    fault to prove the recompute fallback is token-identical too."""
    from paddle_tpu.inference.kv_tier import KVTierManager
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.observability.fleet import LocalStore
    from paddle_tpu.robustness import clear_faults, inject

    n = args.sessions
    rng = np.random.default_rng(args.seed + 101)
    Lp, max_new, bs, slots = 24, 8, 8, 2
    prompts = [rng.integers(0, vocab, (Lp,)).astype(np.int32)
               for _ in range(n + 1)]          # +1 fault-drill session
    bps = -(-(Lp + max_new) // bs)             # blocks per session
    num_blocks = 1 + slots * bps + 2           # ~slots sessions fit
    kw = dict(slots=slots, max_len=64, prefill_buckets=(32,),
              paged_kv=True, kv_block_size=bs, prefill_chunk=16,
              num_kv_blocks=num_blocks,
              quant_weights=qw_mode, quant_kv=qkv_mode)
    tier = KVTierManager(store=LocalStore())
    eng = ContinuousBatchingEngine(model, kv_tier=tier, **kw)

    # reference: identical engine, nothing ever parked (sessions run
    # one at a time so the tiny pool suffices) — the identity oracle
    ref_eng = ContinuousBatchingEngine(model, **kw)
    ref = []
    for p in prompts:
        r = ref_eng.add_request(p, max_new_tokens=max_new)
        ref.append(ref_eng.run()[r][1])
    ref_eng.close()

    def _out_len(rid):
        for req in eng._active:
            if req is not None and req.rid == rid:
                return len(req.out)
        return -1

    t0 = time.perf_counter()
    trajectory, parked = [], []
    # phase 1 — admit, decode >=2 tokens, park: the resident session
    # set grows far past what the pool could ever hold
    for i in range(n):
        rid = eng.add_request(prompts[i], max_new_tokens=max_new)
        while _out_len(rid) < 2:
            eng.step()
        key = eng.park(rid)
        assert key is not None, f"park failed for session {i}"
        parked.append(rid)
        trajectory.append(
            len(eng.parked_rids())
            + sum(1 for q in eng._active if q is not None))
    resident_peak = max(trajectory) if trajectory else 0
    # phase 2 — resume everything (tier promote) and decode to the end
    for rid in parked:
        eng.resume(rid)
    done = eng.run()
    resume_s, parked_s = [], []
    identity = True
    for i, rid in enumerate(parked):
        if list(done[rid][1]) != list(ref[i]):
            identity = False
            print(f"SESSION MISMATCH {i}: parked={list(done[rid][1])} "
                  f"ref={list(ref[i])}", file=sys.stderr)
        st = eng.request_status(rid)
        t = st.timings if st is not None else {}
        resume_s.append(t.get("resume_s", 0.0))
        parked_s.append(t.get("parked_s", 0.0))
    # phase 3 — one session resumes through a dropped tier fetch: the
    # recompute fallback must regenerate the same tokens, never hang
    rid = eng.add_request(prompts[n], max_new_tokens=max_new)
    while _out_len(rid) < 2:
        eng.step()
    eng.park(rid)
    inject("kv_tier.fetch", times=1)
    try:
        eng.resume(rid)
        fb = eng.run()[rid][1]
    finally:
        clear_faults()
    recompute_ok = list(fb) == list(ref[n])
    if not recompute_ok:
        print(f"RECOMPUTE-FALLBACK MISMATCH: {list(fb)} != "
              f"{list(ref[n])}", file=sys.stderr)
    hbm_eq = max(1, (num_blocks - 1) // bps)
    detail = {
        "sessions": n,
        "slots": slots,
        "kv_blocks_total": num_blocks - 1,
        "blocks_per_session": bps,
        "hbm_equivalent_sessions": hbm_eq,
        "resident_peak": resident_peak,
        "sessions_resident_ratio": round(resident_peak / hbm_eq, 2),
        "resident_trajectory": trajectory,
        "drill_wall_s": round(time.perf_counter() - t0, 4),
        "cold_resume": {
            "resume_p50_s": _percentiles(resume_s, ps=(50,))["p50"],
            "resume_p99_s": _percentiles(resume_s, ps=(99,))["p99"],
            "parked_p50_s": _percentiles(parked_s, ps=(50,))["p50"],
        },
        "token_identity": bool(identity),
        "recompute_fallback_identity": bool(recompute_ok),
        "parks": _series("paddle_tpu_serving_session_parks_total"),
        "resumes": _series("paddle_tpu_serving_session_resumes_total"),
        "tier_fetch": _series("paddle_tpu_kv_tier_fetch_total"),
        "tier_spills": _series("paddle_tpu_kv_tier_spills_total"),
        "tier": tier.stats(),
    }
    eng.close()
    return detail


def _run_workload(eng, prompts, arrivals, max_new):
    """Drive the engine under the arrival schedule (wall clock).
    Returns (results {rid: tokens}, rids, t_start, t_end)."""
    from paddle_tpu.robustness import QueueFullError
    results = {}
    rids = [None] * len(prompts)
    waiting = list(range(len(prompts)))
    t0 = time.perf_counter()
    while waiting or eng.pending:
        now = time.perf_counter() - t0
        while waiting and arrivals[waiting[0]] <= now:
            i = waiting[0]
            try:
                rids[i] = eng.add_request(prompts[i],
                                          max_new_tokens=max_new)
                waiting.pop(0)
            except QueueFullError:
                break   # shed: retry on a later loop pass
        if eng.pending:
            eng.step()
            for rid, _p, out in eng.finished():
                results[rid] = out
        elif waiting:
            time.sleep(max(0.0, arrivals[waiting[0]] - now))
    t1 = time.perf_counter()
    return results, rids, t0, t1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--rps", type=float, default=20.0,
                    help="Poisson arrival rate")
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--shared-prefix", type=int, default=24,
                    help="system-prompt tokens shared by every request")
    ap.add_argument("--suffix-max", type=int, default=12)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16,
                    help="chunked-prefill width (paged mode)")
    ap.add_argument("--spec", type=int, default=0,
                    help="n-gram speculative draft length (paged only)")
    ap.add_argument("--steps-per-sync", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workload", choices=("random", "text"),
                    default="random",
                    help="suffix style: 'text' = repeated-phrase tails "
                         "(speculative decoding shows real accept "
                         "rates there)")
    ap.add_argument("--quant-weights", default=None,
                    choices=("int8", "fp8"),
                    help="weight-only quantized engine (default: "
                         "PADDLE_TPU_QUANT_WEIGHTS)")
    ap.add_argument("--quant-kv", default=None, choices=("int8",),
                    help="int8 paged-KV pools (default: "
                         "PADDLE_TPU_QUANT_KV; forces --paged)")
    ap.add_argument("--parity-floor", type=float, default=0.98,
                    help="--check-equivalence under quantization: "
                         "minimum greedy token-match rate vs the bf16 "
                         "engine (hard gate)")
    ap.add_argument("--logit-tol", type=float, default=0.10,
                    help="max relative logit error vs bf16 the parity "
                         "gate tolerates")
    ap.add_argument("--paged", dest="paged", action="store_true",
                    default=None, help="force paged KV on "
                    "(default: PADDLE_TPU_PAGED_KV)")
    ap.add_argument("--no-paged", dest="paged", action="store_false")
    ap.add_argument("--check-equivalence", action="store_true",
                    help="replay through the slot-contiguous engine and "
                         "assert greedy outputs are identical")
    ap.add_argument("--emit", metavar="PATH",
                    help="write the artifact ('auto' → next "
                         "BENCH_serve_rNN.json beside this script)")
    ap.add_argument("--compare", action="store_true",
                    help="regression-check vs the newest "
                         "BENCH_serve_r*.json (exit 1 beyond tolerance)")
    ap.add_argument("--tolerance", type=float, default=0.25)
    from paddle_tpu.inference.router import fleet_serve_replicas
    ap.add_argument("--fleet", type=int,
                    default=fleet_serve_replicas(0),
                    help="route the workload through a ServingRouter "
                         "over N replicas (default PADDLE_TPU_FLEET_"
                         "SERVE; 0 = single engine).  The single-engine "
                         "baseline runs first in the same process so "
                         "detail.fleet carries the measured speedup")
    ap.add_argument("--prefill-replicas", type=int, default=1,
                    help="dedicated prefill replicas in the fleet")
    ap.add_argument("--fleet-mixed", action="store_true",
                    help="homogeneous mixed fleet (no disaggregation)")
    ap.add_argument("--decode-sync", type=int, default=4,
                    help="decode-tier steps_per_sync under "
                         "disaggregation")
    ap.add_argument("--sessions", type=int, default=0,
                    help="run the session-survivability drill: park N "
                         "sessions through the KV tier (host+peer), "
                         "resume them token-identically, and record "
                         "the sessions_resident trajectory in "
                         "detail.sessions")
    ap.add_argument("--decode-slots", type=int, default=0,
                    help="decode-tier slot pool size (0 = same as "
                         "--slots; decode holds sequences far longer "
                         "than prefill, so an asymmetric fleet sizes "
                         "them independently)")
    args = ap.parse_args(argv)
    if args.fleet and args.fleet < 2:
        ap.error("--fleet needs >= 2 replicas")

    import jax

    import paddle_tpu as pp
    from paddle_tpu.inference.kv_cache import paged_kv_enabled
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    # quant knobs resolve ONCE here, then ride explicitly into every
    # engine build — the bf16 equivalence baseline must not re-read env
    from paddle_tpu.inference.kv_cache import quant_kv_mode
    from paddle_tpu.quantization.serving import quant_weights_mode
    qw_mode = quant_weights_mode(args.quant_weights)
    qkv_mode = quant_kv_mode(args.quant_kv)
    paged = paged_kv_enabled() if args.paged is None else args.paged
    if qkv_mode:
        paged = True            # int8 pools are a paged-engine feature
    dev = jax.devices()[0]
    pp.seed(args.seed)
    if dev.platform == "tpu":
        # serving-proportioned model that decodes comfortably on one chip
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=3584,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=8,
            max_position_embeddings=max(2 * args.max_len, 2048),
            rope_theta=500000.0, dtype="bfloat16")
    else:  # CI/CPU smoke
        cfg = LlamaConfig.tiny(
            max_position_embeddings=max(args.max_len, 128))
    model = LlamaForCausalLM(cfg)

    prompts, arrivals = _workload(args, cfg.vocab_size)
    if args.fleet:
        paged = True            # the fleet handoff rides paged blocks
    eng = _build_engine(model, args, paged,
                        quant_weights=qw_mode or "0",
                        quant_kv=qkv_mode or "0")
    # explicit AOT warmup outside the timed window: compiles (or, with
    # PADDLE_TPU_COMPILE_CACHE=1, deserialize-and-loads) every serving
    # executable up front — the replica cold-start cost is a measured
    # number, not a first-request latency spike
    t_warm0 = time.perf_counter()
    warm_stats = eng.aot_warmup()
    warmup_s = time.perf_counter() - t_warm0
    from paddle_tpu.observability.device_profiler import compile_records
    warm_recs = [r for r in compile_records()
                 if r.target in warm_stats]
    # one throwaway request flushes any remaining lazy init
    w = eng.add_request(prompts[0][: max(2, len(prompts[0]) // 2)],
                        max_new_tokens=2)
    eng.run()
    st_warm = eng.request_status(w)
    first_token_s = (st_warm.timings.get("ttft_s")
                     if st_warm is not None else None)

    base = _run_stats(eng, prompts, arrivals, args)

    fleet_detail = None
    if args.fleet:
        # the fleet under test: same workload, fresh arrival clock; the
        # run above is the in-process single-engine baseline the
        # speedup/TTFT-ratio acceptance numbers divide by
        router = _build_router(model, args,
                               quant_weights=qw_mode or "0",
                               quant_kv=qkv_mode or "0")
        for rep in router._replicas.values():
            stats = rep.engine.aot_warmup()
            warm_stats.update(stats)
        w = router.add_request(
            prompts[0][: max(2, len(prompts[0]) // 2)],
            max_new_tokens=2)
        router.run()
        fleet = _run_stats(router, prompts, arrivals, args)
        serving = fleet
        serving_eng = router
        base_ttft99 = _percentiles(base["ttfts"])["p99"]
        fl_ttft99 = _percentiles(fleet["ttfts"])["p99"]
        fleet_detail = {
            "replicas": args.fleet,
            "prefill_replicas": (0 if args.fleet_mixed
                                 else max(1, args.prefill_replicas)),
            "decode_steps_per_sync": (args.decode_sync if not args.spec
                                      else 1),
            "baseline_tokens_per_s": round(base["tok_s"], 2),
            "speedup": round(fleet["tok_s"] / base["tok_s"], 4)
            if base["tok_s"] else None,
            "baseline_ttft_p99_s": base_ttft99,
            "ttft_p99_ratio": round(fl_ttft99 / base_ttft99, 4)
            if base_ttft99 and fl_ttft99 else None,
            "baseline_tpot_p99_s": _percentiles(base["tpots"])["p99"],
            "route_p50_s": _percentiles(fleet["route_s"],
                                        ps=(50,))["p50"],
            "handoff_p50_s": _percentiles(fleet["handoff_s"],
                                          ps=(50,))["p50"],
            "handoffs": _series("paddle_tpu_router_handoffs_total"),
            "dispatch": _series("paddle_tpu_router_affinity_total"),
            "requeues": _series("paddle_tpu_router_requeues_total"),
            "replica_deaths": _series(
                "paddle_tpu_router_replica_deaths_total"),
            "handoff_bytes": _series(
                "paddle_tpu_router_handoff_bytes_total"),
        }
    else:
        serving = base
        serving_eng = eng

    sessions_detail = None
    if args.sessions:
        sessions_detail = _session_drill(model, args, cfg.vocab_size,
                                         qw_mode or "0",
                                         qkv_mode or "0")
        print("sessions_resident trajectory (parked+active): "
              + " ".join(str(v) for v in
                         sessions_detail["resident_trajectory"]),
              file=sys.stderr)
        print(f"sessions_resident "
              f"peak={sessions_detail['resident_peak']} "
              f"hbm_equivalent="
              f"{sessions_detail['hbm_equivalent_sessions']} "
              f"ratio={sessions_detail['sessions_resident_ratio']} "
              f"token_identity={sessions_detail['token_identity']} "
              f"recompute_fallback="
              f"{sessions_detail['recompute_fallback_identity']}",
              file=sys.stderr)

    results, rids = serving["results"], serving["rids"]
    reused_tokens = serving["reused_tokens"]
    accept_rates = serving["accept_rates"]
    wall = serving["wall"]
    total_tokens = serving["tokens"]
    tok_s = serving["tok_s"]
    ttfts, tpots = serving["ttfts"], serving["tpots"]
    ttft = _percentiles(ttfts)
    tpot = _percentiles(tpots)

    # SLO attainment from the per-request timings (same targets the
    # engine's paddle_tpu_serving_slo_total counters judge against) —
    # the serving twin of training's goodput, guarded by --compare
    from paddle_tpu.observability.goodput import slo_targets
    targets = slo_targets()
    slo = {"ttft_target_s": targets["ttft"],
           "tpot_target_s": targets["tpot"],
           "ttft": (sum(1 for v in ttfts if v <= targets["ttft"])
                    / len(ttfts) if ttfts and targets["ttft"] > 0
                    else None),
           "tpot": (sum(1 for v in tpots if v <= targets["tpot"])
                    / len(tpots) if tpots and targets["tpot"] > 0
                    else None)}

    detail = {
        "requests": args.requests,
        "completed": len(results),
        "rps": args.rps,
        "wall_s": round(wall, 4),
        "generated_tokens": total_tokens,
        "ttft_p50_s": ttft["p50"], "ttft_p99_s": ttft["p99"],
        "tpot_p50_s": tpot["p50"], "tpot_p99_s": tpot["p99"],
        "paged": bool(paged),
        "spec_decode": args.spec,
        "steps_per_sync": args.steps_per_sync,
        "workload": args.workload,
        "shared_prefix": args.shared_prefix,
        "device": getattr(dev, "device_kind", dev.platform),
        "prefix_hit_tokens": reused_tokens,
        "slo_attainment": slo,
        "prefix_cache": _series("paddle_tpu_serving_prefix_cache_total"),
        "spec_tokens": _series("paddle_tpu_serving_spec_tokens_total"),
        "spec_accept_rate_mean": (float(np.mean(accept_rates))
                                  if accept_rates else None),
    }
    # per-cause tail attribution (ISSUE 20): fold every request's
    # timings through the forensics cause decomposition so --compare
    # can flag a dominant-cause flip or a cold-resume share regression
    from paddle_tpu.observability import forensics
    detail["tail_attribution"] = forensics.summarize_attributions(
        [forensics.attribute(t) for t in serving["timings"]])
    if fleet_detail is not None:
        detail["fleet"] = fleet_detail
    if sessions_detail is not None:
        detail["sessions"] = sessions_detail
    # replica cold-start ledger (ROADMAP 5): wall time to acquire every
    # serving executable (trace+compile live, or deserialize on a
    # compile-cache hit), TTFT of the first request after warmup, and
    # the cache counters that say which path this boot took
    from paddle_tpu import compile_cache
    cache_series = _series("paddle_tpu_compile_cache_total")
    detail["cold_start"] = {
        "trace_s": round(sum(r.lower_s for r in warm_recs), 4),
        "compile_or_load_s": round(
            sum(r.compile_s for r in warm_recs), 4),
        "warmup_wall_s": round(warmup_s, 4),
        "first_token_s": (round(first_token_s, 4)
                          if first_token_s else None),
        "executables": len(warm_stats),
        "cache_hits": sum(1 for r in warm_recs if r.cached),
        "cache_enabled": compile_cache.enabled(),
        "cache": {
            "hit": sum(v for k, v in cache_series.items()
                       if k.endswith("/hit")),
            "miss": sum(v for k, v in cache_series.items()
                        if k.endswith("/miss")),
            "deserialize_error": sum(
                v for k, v in cache_series.items()
                if k.endswith("/deserialize_error")),
        },
    }
    # measurement ledger (PADDLE_TPU_CALIBRATION=1): serving's decode
    # latency joins the corpus (provenance bench_serve; no model
    # prediction, so it contributes measurement coverage, not a
    # residual) and the artifact carries the same calibration-health
    # section bench.py does, guarded identically by --compare
    from paddle_tpu.observability import calibration
    if calibration.enabled() and tpot["p50"]:
        calibration.ledger().record(
            "serve_decode", (args.slots, args.max_len),
            measured_s=float(tpot["p50"]), provenance="bench_serve")
    detail["calibration"] = calibration.bench_detail()
    if paged:
        detail["kv_blocks_total"] = eng._num_blocks - 1
        detail["kv_blocks_peak_used"] = eng._blocks_used_peak
        detail["kv_block_utilization"] = round(
            eng._blocks_used_peak / max(1, eng._num_blocks - 1), 4)
        detail["kv_events"] = {
            "evictions": _series("paddle_tpu_serving_kv_evictions_total"),
            "cow": _series("paddle_tpu_serving_kv_cow_copies_total"),
            "alloc_failures": _series(
                "paddle_tpu_serving_kv_alloc_failures_total"),
        }
    if qw_mode or qkv_mode:
        # the quantized-serving capacity/accuracy ledger: blocks ratio
        # is the tentpole's measured capacity claim (int8 pools hold
        # itemsize-ratio more blocks at the SAME payload HBM bytes);
        # token_match_rate / max_logit_err land here when
        # --check-equivalence runs the parity gate below
        base_blocks = args.slots * (-(-args.max_len // args.block_size))
        detail["quant"] = {
            "weights": qw_mode,
            "kv": qkv_mode,
            "kv_blocks_ratio": (round((eng._num_blocks - 1)
                                      / base_blocks, 4)
                                if paged else None),
            "kv_pool_bytes": eng._pool.nbytes if paged else None,
            "quant_paths": _series(
                "paddle_tpu_quant_kernel_path_total"),
            "token_match_rate": None,
            "max_logit_err": None,
        }
    result = {
        "metric": "serving_tokens_per_s",
        "value": round(tok_s, 2),
        "unit": "tokens/s",
        "detail": detail,
    }

    if args.check_equivalence:
        # replay sequentially through the slot-contiguous bf16 engine.
        # Unquantized: paged/routed greedy decode must be token-for-
        # token IDENTICAL.  Quantized: the accuracy-parity gate — the
        # greedy token-match rate must clear --parity-floor and the
        # weight-quant logit error must stay under --logit-tol, so
        # quantization can never silently rot quality.  Engines close
        # first: the weight conversion is refcounted on the model and
        # the baseline must see the original bf16 weights.
        serving_eng.close()
        if serving_eng is not eng:
            eng.close()
        base_eng = _build_engine(model, argparse.Namespace(
            **{**vars(args), "spec": 0}), paged=False)
        quant = bool(qw_mode or qkv_mode)
        mismatches = 0
        matched = total = 0
        for i, rid in enumerate(rids):
            b = base_eng.add_request(prompts[i],
                                     max_new_tokens=args.max_new)
            got = base_eng.run()[b][1]
            ours = results.get(rid) or []
            # greedy token-match counts up to and including the FIRST
            # divergence per request: past it the two engines decode
            # different contexts, so positionwise comparison would
            # charge one flipped argmax as a fully-wrong tail.  This is
            # P(token survives quantization | identical context) — the
            # spec-decode-literature greedy-equivalence metric.
            lcp = 0
            while lcp < min(len(got), len(ours)) and \
                    got[lcp] == ours[lcp]:
                lcp += 1
            diverged = lcp < max(len(got), len(ours))
            matched += lcp
            total += lcp + (1 if diverged else 0)
            if got != ours:
                mismatches += 1
                if not quant:
                    print(f"EQUIVALENCE MISMATCH req {i}: paged="
                          f"{ours} baseline={got}", file=sys.stderr)
        match_rate = matched / total if total else 0.0
        result["detail"]["equivalence"] = {
            "checked": len(rids), "mismatches": mismatches,
            "token_match_rate": round(match_rate, 4)}
        if paged and args.shared_prefix >= 2 * args.block_size and \
                reused_tokens < 1:
            print("EQUIVALENCE: expected >=1 prefix-cache hit on the "
                  "shared-prompt workload, saw none", file=sys.stderr)
            mismatches += 1
        if quant:
            q = result["detail"]["quant"]
            q["token_match_rate"] = round(match_rate, 4)
            failed = match_rate < args.parity_floor
            if qw_mode:
                from paddle_tpu.quantization.serving import \
                    parity_report
                rep = parity_report(model, qw_mode,
                                    prompts[0][None, :])
                q["max_logit_err"] = round(rep["max_logit_err"], 6)
                q["rel_logit_err"] = round(rep["rel_logit_err"], 6)
                if rep["rel_logit_err"] > args.logit_tol:
                    failed = True
                    print(f"PARITY: rel logit error "
                          f"{rep['rel_logit_err']:.4f} exceeds "
                          f"--logit-tol {args.logit_tol}",
                          file=sys.stderr)
            if failed or match_rate < args.parity_floor:
                print(f"PARITY GATE FAILED: token_match_rate="
                      f"{match_rate:.4f} (floor {args.parity_floor})",
                      file=sys.stderr)
                print(json.dumps(result))
                return 1
            print(f"parity ok: {len(rids)} requests, token_match_rate="
                  f"{match_rate:.4f} >= {args.parity_floor}, "
                  f"logit_err={q.get('rel_logit_err')}",
                  file=sys.stderr)
        elif mismatches:
            print(json.dumps(result))
            return 1
        else:
            print(f"equivalence ok: {len(rids)} requests, paged == "
                  f"baseline, prefix_hit_tokens={reused_tokens}",
                  file=sys.stderr)

    print(json.dumps(result))

    if args.emit:
        here = os.path.dirname(os.path.abspath(__file__))
        path = args.emit
        if path == "auto":
            path = os.path.join(
                here, f"BENCH_serve_r{_next_serve_round(here):02d}.json")
        with open(path, "w") as f:
            json.dump({"schema": "bench_serve", "parsed": result}, f,
                      indent=1)
        print(f"wrote {path}", file=sys.stderr)

    if args.compare:
        import bench as _bench
        prev = _bench._prev_serve_record()
        if prev is None:
            print(json.dumps({"bench_compare": {
                "ok": True, "note": "no previous BENCH_serve artifact"}}),
                file=sys.stderr)
            return 0
        regressions = _bench.compare_serve_records(result, prev,
                                                   args.tolerance)
        print(json.dumps({"bench_compare": {
            "ok": not regressions, "tolerance": args.tolerance,
            "prev_value": prev.get("value"),
            "regressions": regressions}}), file=sys.stderr)
        if regressions:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
