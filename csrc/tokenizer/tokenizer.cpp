// Native WordPiece tokenizer — the faster_tokenizer analog.
//
// Reference role: paddle/fluid/operators/string/faster_tokenizer_op.cc
// (BERT-style tokenize inside the graph) + phi/kernels/strings/.  Here
// tokenization is host-side data-plane work (TPU kernels never see
// strings), so the native piece is a standalone C++ tokenizer bound over
// ctypes and fed to the datafeed: basic pretokenization (whitespace +
// punctuation split, optional lowercasing with ASCII + Latin-1 folding),
// then greedy longest-match-first WordPiece with "##" continuations.
//
// Lowercase folding is ASCII-only (std::tolower, C locale): non-ASCII
// UTF-8 bytes pass through unfolded, so accented vocab entries must be
// stored in their cased form.  Duplicate vocab lines keep the FIRST id
// (idx still advances, so later lines stay aligned with their row).
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   tok_create(vocab_path, do_lower) -> handle
//   tok_encode(handle, text, out_ids, max_len) -> n_tokens (ids include
//     no specials; the Python wrapper adds [CLS]/[SEP] per config)
//   tok_token_to_id / tok_id_count / tok_destroy
#include <cctype>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, int64_t> vocab;
  bool lower = true;
  int64_t unk = 0;
  size_t max_word_chars = 100;
};

bool is_punct(unsigned char c) {
  return std::ispunct(c) != 0;
}

// split into words: whitespace separates, punctuation is its own token
std::vector<std::string> pretokenize(const std::string& text, bool lower) {
  std::vector<std::string> words;
  std::string cur;
  for (unsigned char c : text) {
    if (std::isspace(c)) {
      if (!cur.empty()) words.push_back(cur);
      cur.clear();
    } else if (is_punct(c)) {
      if (!cur.empty()) words.push_back(cur);
      cur.clear();
      words.emplace_back(1, static_cast<char>(c));
    } else {
      cur.push_back(lower ? static_cast<char>(std::tolower(c))
                          : static_cast<char>(c));
    }
  }
  if (!cur.empty()) words.push_back(cur);
  return words;
}

// greedy longest-match-first WordPiece (the BERT algorithm)
void wordpiece(const Tokenizer& t, const std::string& word,
               std::vector<int64_t>* out) {
  if (word.size() > t.max_word_chars) {
    out->push_back(t.unk);
    return;
  }
  size_t start = 0;
  std::vector<int64_t> pieces;
  while (start < word.size()) {
    size_t end = word.size();
    int64_t cur_id = -1;
    while (start < end) {
      std::string sub = word.substr(start, end - start);
      if (start > 0) sub = "##" + sub;
      auto it = t.vocab.find(sub);
      if (it != t.vocab.end()) {
        cur_id = it->second;
        break;
      }
      --end;
    }
    if (cur_id < 0) {  // no piece matches: whole word is UNK
      out->push_back(t.unk);
      return;
    }
    pieces.push_back(cur_id);
    start = end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
}

}  // namespace

extern "C" {

void* tok_create(const char* vocab_path, int do_lower) {
  auto* t = new Tokenizer();
  t->lower = do_lower != 0;
  std::ifstream in(vocab_path);
  if (!in) {
    delete t;
    return nullptr;
  }
  std::string line;
  int64_t idx = 0;
  while (std::getline(in, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    t->vocab.emplace(line, idx++);
  }
  auto unk = t->vocab.find("[UNK]");
  t->unk = unk != t->vocab.end() ? unk->second : 0;
  return t;
}

void tok_destroy(void* handle) {
  delete static_cast<Tokenizer*>(handle);
}

int64_t tok_id_count(void* handle) {
  return static_cast<int64_t>(static_cast<Tokenizer*>(handle)->vocab.size());
}

int64_t tok_token_to_id(void* handle, const char* token) {
  auto* t = static_cast<Tokenizer*>(handle);
  auto it = t->vocab.find(token);
  return it != t->vocab.end() ? it->second : -1;
}

// returns number of ids written (<= max_len; truncates past max_len)
int64_t tok_encode(void* handle, const char* text, int64_t* out_ids,
                   int64_t max_len) {
  auto* t = static_cast<Tokenizer*>(handle);
  std::vector<int64_t> ids;
  for (const auto& w : pretokenize(text, t->lower)) wordpiece(*t, w, &ids);
  int64_t n = static_cast<int64_t>(ids.size());
  if (n > max_len) n = max_len;
  if (n > 0) std::memcpy(out_ids, ids.data(), n * sizeof(int64_t));
  return n;
}

}  // extern "C"
