// TCPStore — native key/value rendezvous for multi-host bootstrap.
//
// TPU-native equivalent of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.{h,cc} + socket.cpp):
// a tiny KV server the first host runs, that all hosts use to exchange
// coordinator addresses / barrier before jax.distributed takes over, plus
// generic set/get/add/wait for user-level control-plane sync (the role
// brpc MessageBus / c_gen_nccl_id play in the reference).
//
// Protocol (all little-endian):
//   request:  u8 cmd | u32 keylen | key | (SET: u32 vallen | val)
//                                        (ADD: i64 delta)
//                                        (ADDTOK: i64 delta |
//                                         u32 toklen | token)
//                                        (GET/CHECK: nothing)
//   response: SET -> u8 ok
//             GET -> u32 vallen | val   (vallen == 0xFFFFFFFF => not found)
//             ADD -> i64 new_value
//             ADDTOK -> i64 new_value (dedup: a token the server has
//                       already applied returns the RECORDED result
//                       without re-adding — retry-safe counters: a
//                       client whose response was lost on the wire can
//                       resend the same op id and never double-count)
//             CHECK -> u8 present
//
// Exposed as extern "C" for ctypes (no pybind11 in this image).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kCheck = 4,
                     kAddTok = 5 };

// Bounded op-id dedup ledger for kAddTok: retries land within seconds,
// so FIFO-evicting old entries never forgets a token that could still
// be legitimately resent, while a long-lived store stays O(cap) memory.
constexpr size_t kTokenCap = 65536;

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class Server {
 public:
  explicit Server(int port) : port_(port) {}

  bool start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) < 0)
      return false;
    if (::listen(listen_fd_, 128) < 0) return false;
    running_.store(true);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  // Wait until at most `max_clients` connections remain (the master's own
  // client fd counts).  Lets the master drain peers before stop(): a peer
  // whose final barrier poll is in flight gets its response instead of a
  // reset connection (torch TCPStore wait_for_workers semantics).
  bool wait_clients(int max_clients, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (true) {
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        if (static_cast<int>(conn_fds_.size()) <= max_clients) return true;
      }
      if (std::chrono::steady_clock::now() > deadline) return false;
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  }

  void stop() {
    running_.store(false);
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> to_join;
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      // unblock Serve threads still parked in recv on live client
      // connections (clients need not have closed their end)
      for (int fd : conn_fds_)
        ::shutdown(fd, SHUT_RDWR);
      to_join.swap(conn_threads_);
    }
    // join OUTSIDE the lock: Serve threads take conn_mu_ to deregister
    for (auto& t : to_join)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (running_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (!running_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(conn_mu_);
      conn_fds_.push_back(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (true) {
      uint8_t cmd;
      if (!recv_all(fd, &cmd, 1)) break;
      uint32_t keylen;
      if (!recv_all(fd, &keylen, 4)) break;
      std::string key(keylen, '\0');
      if (keylen && !recv_all(fd, key.data(), keylen)) break;

      if (cmd == kSet) {
        uint32_t vallen;
        if (!recv_all(fd, &vallen, 4)) break;
        std::string val(vallen, '\0');
        if (vallen && !recv_all(fd, val.data(), vallen)) break;
        {
          std::lock_guard<std::mutex> lk(mu_);
          data_[key] = std::move(val);
        }
        cv_.notify_all();
        uint8_t ok = 1;
        if (!send_all(fd, &ok, 1)) break;
      } else if (cmd == kGet) {
        std::string val;
        bool found = false;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = data_.find(key);
          if (it != data_.end()) {
            val = it->second;
            found = true;
          }
        }
        uint32_t vallen = found ? static_cast<uint32_t>(val.size())
                                : 0xFFFFFFFFu;
        if (!send_all(fd, &vallen, 4)) break;
        if (found && !val.empty() && !send_all(fd, val.data(), val.size()))
          break;
      } else if (cmd == kAdd || cmd == kAddTok) {
        int64_t delta;
        if (!recv_all(fd, &delta, 8)) break;
        std::string token;
        if (cmd == kAddTok) {
          uint32_t toklen;
          if (!recv_all(fd, &toklen, 4)) break;
          token.resize(toklen);
          if (toklen && !recv_all(fd, token.data(), toklen)) break;
        }
        int64_t result;
        {
          std::lock_guard<std::mutex> lk(mu_);
          bool replay = false;
          if (!token.empty()) {
            auto seen = applied_tokens_.find(token);
            if (seen != applied_tokens_.end()) {
              result = seen->second;  // duplicate op id: replay the
              replay = true;          // recorded result, apply nothing
            }
          }
          if (!replay) {
            int64_t cur = 0;
            auto it = data_.find(key);
            if (it != data_.end() && it->second.size() == 8)
              std::memcpy(&cur, it->second.data(), 8);
            cur += delta;
            std::string val(8, '\0');
            std::memcpy(val.data(), &cur, 8);
            data_[key] = std::move(val);
            result = cur;
            if (!token.empty()) {
              applied_tokens_.emplace(token, result);
              token_fifo_.push_back(token);
              while (token_fifo_.size() > kTokenCap) {
                applied_tokens_.erase(token_fifo_.front());
                token_fifo_.pop_front();
              }
            }
          }
        }
        cv_.notify_all();
        if (!send_all(fd, &result, 8)) break;
      } else if (cmd == kCheck) {
        uint8_t present;
        {
          std::lock_guard<std::mutex> lk(mu_);
          present = data_.count(key) ? 1 : 0;
        }
        if (!send_all(fd, &present, 1)) break;
      } else {
        break;
      }
    }
    {
      // drop from conn_fds_ BEFORE closing so stop() can never shutdown a
      // recycled descriptor number belonging to something else
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (auto it = conn_fds_.begin(); it != conn_fds_.end(); ++it) {
        if (*it == fd) {
          conn_fds_.erase(it);
          break;
        }
      }
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::vector<std::thread> conn_threads_;
  std::vector<int> conn_fds_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  std::unordered_map<std::string, int64_t> applied_tokens_;
  std::deque<std::string> token_fifo_;
};

}  // namespace

extern "C" {

void* tcpstore_server_start(int port) {
  auto* s = new Server(port);
  if (!s->start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int tcpstore_server_wait_clients(void* handle, int max_clients,
                                 int timeout_ms) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return -1;
  return s->wait_clients(max_clients, timeout_ms) ? 0 : -1;
}

void tcpstore_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (s) {
    s->stop();
    delete s;
  }
}

int tcpstore_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

int tcpstore_set(int fd, const char* key, const uint8_t* val, int vallen) {
  uint8_t cmd = kSet;
  uint32_t keylen = static_cast<uint32_t>(std::strlen(key));
  uint32_t vl = static_cast<uint32_t>(vallen);
  if (!send_all(fd, &cmd, 1) || !send_all(fd, &keylen, 4) ||
      !send_all(fd, key, keylen) || !send_all(fd, &vl, 4) ||
      (vallen && !send_all(fd, val, vl)))
    return -1;
  uint8_t ok;
  return recv_all(fd, &ok, 1) && ok == 1 ? 0 : -1;
}

// Returns value length, -1 on error, -2 if not present.
int tcpstore_get(int fd, const char* key, uint8_t* out, int out_cap) {
  uint8_t cmd = kGet;
  uint32_t keylen = static_cast<uint32_t>(std::strlen(key));
  if (!send_all(fd, &cmd, 1) || !send_all(fd, &keylen, 4) ||
      !send_all(fd, key, keylen))
    return -1;
  uint32_t vallen;
  if (!recv_all(fd, &vallen, 4)) return -1;
  if (vallen == 0xFFFFFFFFu) return -2;
  if (vallen > static_cast<uint32_t>(out_cap)) {
    // drain and report error
    std::vector<char> sink(vallen);
    recv_all(fd, sink.data(), vallen);
    return -1;
  }
  if (vallen && !recv_all(fd, out, vallen)) return -1;
  return static_cast<int>(vallen);
}

int64_t tcpstore_add(int fd, const char* key, int64_t delta) {
  uint8_t cmd = kAdd;
  uint32_t keylen = static_cast<uint32_t>(std::strlen(key));
  if (!send_all(fd, &cmd, 1) || !send_all(fd, &keylen, 4) ||
      !send_all(fd, key, keylen) || !send_all(fd, &delta, 8))
    return INT64_MIN;
  int64_t result;
  if (!recv_all(fd, &result, 8)) return INT64_MIN;
  return result;
}

// Idempotent add: `token` is a caller-unique op id; resending the same
// token replays the first application's result instead of re-adding.
int64_t tcpstore_add_tok(int fd, const char* key, int64_t delta,
                         const char* token) {
  uint8_t cmd = kAddTok;
  uint32_t keylen = static_cast<uint32_t>(std::strlen(key));
  uint32_t toklen = static_cast<uint32_t>(std::strlen(token));
  if (!send_all(fd, &cmd, 1) || !send_all(fd, &keylen, 4) ||
      !send_all(fd, key, keylen) || !send_all(fd, &delta, 8) ||
      !send_all(fd, &toklen, 4) ||
      (toklen && !send_all(fd, token, toklen)))
    return INT64_MIN;
  int64_t result;
  if (!recv_all(fd, &result, 8)) return INT64_MIN;
  return result;
}

int tcpstore_check(int fd, const char* key) {
  uint8_t cmd = kCheck;
  uint32_t keylen = static_cast<uint32_t>(std::strlen(key));
  if (!send_all(fd, &cmd, 1) || !send_all(fd, &keylen, 4) ||
      !send_all(fd, key, keylen))
    return -1;
  uint8_t present;
  if (!recv_all(fd, &present, 1)) return -1;
  return present;
}

void tcpstore_close(int fd) { ::close(fd); }

}  // extern "C"
