// Native token data-feed: mmap'd corpus -> prefetched LM batches.
//
// TPU-native equivalent of the reference's C++ data pipeline
// (paddle/fluid/framework/data_feed.cc + data_set.cc: multi-threaded file
// parsers feeding trainer workers through channels, and the
// buffered_reader/LoDTensorBlockingQueue pair behind paddle.io.DataLoader).
//
// Design: the corpus is a flat binary file of int32 token ids.  Worker
// threads assemble [batch, seq_len+1] sample windows into a bounded ring of
// reusable buffers (double-buffering against the consumer), so Python's
// only per-batch work is one memcpy into a numpy array via ctypes.
// Shuffling uses a splitmix64-derived bijective permutation over window
// indices — O(1) state, deterministic per (seed, epoch).

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// Feistel-style bijection over [0, n): cheap deterministic shuffle without
// materialising a permutation array (corpus may have billions of windows).
uint64_t permute_index(uint64_t i, uint64_t n, uint64_t seed) {
  if (n <= 1) return 0;
  int bits = 64 - __builtin_clzll(n - 1);  // bits to cover [0, n)
  int half = (bits + 1) / 2;
  uint64_t half_mask = (1ull << half) - 1;
  uint64_t x = i;
  do {  // 4-round Feistel on bit-halves; cycle-walk back into [0, n)
    uint64_t l = x & half_mask;
    uint64_t r = x >> half;
    for (int round = 0; round < 4; ++round) {
      uint64_t nl = r;
      r = l ^ (splitmix64(r + seed + static_cast<uint64_t>(round)) &
               half_mask);
      l = nl;
    }
    x = (r << half) | l;
  } while (x >= n);
  return x;
}

struct Batch {
  std::vector<int32_t> data;  // [batch, seq_len + 1]
};

class DataFeed {
 public:
  DataFeed(const char* path, int64_t seq_len, int64_t batch_size,
           int shuffle, uint64_t seed, int num_threads, int queue_depth)
      : seq_len_(seq_len),
        batch_(batch_size),
        shuffle_(shuffle),
        seed_(seed),
        depth_(queue_depth < 2 ? 2 : queue_depth) {
    fd_ = ::open(path, O_RDONLY);
    if (fd_ < 0) return;
    struct stat st {};
    if (::fstat(fd_, &st) != 0) return;
    n_tokens_ = static_cast<int64_t>(st.st_size) / 4;
    if (n_tokens_ < seq_len_ + 1) return;
    map_ = static_cast<const int32_t*>(
        ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
               MAP_PRIVATE, fd_, 0));
    if (map_ == MAP_FAILED) {
      map_ = nullptr;
      return;
    }
    ::madvise(const_cast<int32_t*>(map_), static_cast<size_t>(st.st_size),
              MADV_SEQUENTIAL);
    n_windows_ = n_tokens_ / (seq_len_ + 1);
    n_batches_ = n_windows_ / batch_;
    ok_ = n_batches_ > 0;
    if (!ok_) return;
    running_.store(true);
    int workers = num_threads < 1 ? 1 : num_threads;
    for (int t = 0; t < workers; ++t)
      threads_.emplace_back([this, t, workers] { Worker(t, workers); });
  }

  ~DataFeed() {
    running_.store(false);
    cv_space_.notify_all();
    cv_item_.notify_all();
    for (auto& t : threads_)
      if (t.joinable()) t.join();
    if (map_) ::munmap(const_cast<int32_t*>(map_), n_tokens_ * 4);
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return ok_; }
  int64_t num_batches() const { return n_batches_; }
  int64_t num_tokens() const { return n_tokens_; }

  // Copy the next batch (in epoch order) into out[batch * (seq_len+1)].
  // Returns 0 on success, 1 on epoch end (no copy), -1 on error.
  int Next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_item_.wait(lk, [this] {
      return !queue_.empty() || !running_.load();
    });
    if (queue_.empty()) return -1;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    lk.unlock();
    cv_space_.notify_one();
    std::memcpy(out, b.data.data(), b.data.size() * 4);
    int64_t consumed = consumed_.fetch_add(1) + 1;
    return consumed % n_batches_ == 0 ? 1 : 0;
  }

 private:
  void Worker(int, int) {
    // workers stride the global batch sequence; batches are produced in
    // order via a ticketing scheme so epochs stay deterministic
    while (running_.load()) {
      int64_t ticket = next_ticket_.fetch_add(1);
      int64_t epoch = ticket / n_batches_;
      int64_t bidx = ticket % n_batches_;
      Batch b;
      b.data.resize(static_cast<size_t>(batch_) * (seq_len_ + 1));
      for (int64_t s = 0; s < batch_; ++s) {
        uint64_t widx = static_cast<uint64_t>(bidx) * batch_ + s;
        if (shuffle_)
          widx = permute_index(widx, static_cast<uint64_t>(n_windows_),
                               seed_ + static_cast<uint64_t>(epoch));
        const int32_t* src = map_ + widx * (seq_len_ + 1);
        std::memcpy(b.data.data() + s * (seq_len_ + 1), src,
                    static_cast<size_t>(seq_len_ + 1) * 4);
      }
      // in-order handoff
      std::unique_lock<std::mutex> lk(mu_);
      cv_space_.wait(lk, [this, ticket] {
        return (!running_.load()) ||
               (static_cast<int64_t>(queue_.size()) < depth_ &&
                ticket == emit_ticket_.load());
      });
      if (!running_.load()) return;
      queue_.push_back(std::move(b));
      emit_ticket_.fetch_add(1);
      lk.unlock();
      cv_item_.notify_one();
      cv_space_.notify_all();
    }
  }

  int64_t seq_len_, batch_;
  int shuffle_;
  uint64_t seed_;
  int64_t depth_;
  int fd_ = -1;
  const int32_t* map_ = nullptr;
  int64_t n_tokens_ = 0, n_windows_ = 0, n_batches_ = 0;
  bool ok_ = false;

  std::atomic<bool> running_{false};
  std::atomic<int64_t> next_ticket_{0};
  std::atomic<int64_t> emit_ticket_{0};
  std::atomic<int64_t> consumed_{0};
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::condition_variable cv_item_, cv_space_;
  std::deque<Batch> queue_;
};

}  // namespace

extern "C" {

void* datafeed_open(const char* path, int64_t seq_len, int64_t batch_size,
                    int shuffle, uint64_t seed, int num_threads,
                    int queue_depth) {
  auto* f = new DataFeed(path, seq_len, batch_size, shuffle, seed,
                         num_threads, queue_depth);
  if (!f->ok()) {
    delete f;
    return nullptr;
  }
  return f;
}

int64_t datafeed_num_batches(void* h) {
  return static_cast<DataFeed*>(h)->num_batches();
}

int64_t datafeed_num_tokens(void* h) {
  return static_cast<DataFeed*>(h)->num_tokens();
}

int datafeed_next(void* h, int32_t* out) {
  return static_cast<DataFeed*>(h)->Next(out);
}

void datafeed_close(void* h) { delete static_cast<DataFeed*>(h); }

}  // extern "C"
