// Native inference predictor: loads a paddle_tpu jit.save artifact and
// executes it through the PJRT C API of any PJRT plugin (libtpu / axon /
// any GetPjrtApi-exporting .so).
//
// Reference role: paddle/fluid/inference/api/analysis_predictor.cc:1665 —
// the C++ serving engine around the saved inference artifact.  The
// TPU-native translation: the artifact's program is StableHLO
// (<path>.pdstablehlo, written by paddle_tpu.jit.save), parameters are an
// uncompressed .npz (<path>.pdiparams.npz), and the runtime is PJRT —
// create client, compile, upload params once, execute per request.
//
// Exposed as a small C ABI for the ctypes binding
// (paddle_tpu/inference/native.py).  C++17, deps: libdl only (the PJRT C
// API header is a self-contained C header from the installed XLA).

#include <dlfcn.h>

#include <cstdint>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#include "tensorflow/compiler/xla/pjrt/c/pjrt_c_api.h"

namespace {

// ---------------------------------------------------------------- helpers

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

std::string pjrt_error_message(const PJRT_Api* api, PJRT_Error* err) {
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  std::string msg(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return msg;
}

#define PJRT_CHECK(api, call)                                   \
  do {                                                          \
    PJRT_Error* _err = (call);                                  \
    if (_err != nullptr) {                                      \
      set_error(#call ": " + pjrt_error_message((api), _err));  \
      return false;                                             \
    }                                                           \
  } while (0)

bool read_file(const std::string& path, std::string* out) {
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    set_error("cannot open " + path);
    return false;
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  *out = ss.str();
  return true;
}

// ------------------------------------------------- minimal npz/npy reader
// np.savez writes a ZIP archive with STORED (uncompressed) .npy members.

struct NpyArray {
  std::string name;                 // member name without ".npy"
  std::string dtype;                // numpy descr, e.g. "<f4"
  std::vector<int64_t> shape;
  const char* data = nullptr;       // points into the archive buffer
  size_t nbytes = 0;
};

uint16_t rd16(const char* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
uint32_t rd32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

bool parse_npy(const char* p, size_t n, NpyArray* out) {
  if (n < 10 || std::memcmp(p, "\x93NUMPY", 6) != 0) {
    set_error("bad npy magic");
    return false;
  }
  int major = p[6];
  size_t hlen, hoff;
  if (major == 1) {
    hlen = rd16(p + 8);
    hoff = 10;
  } else {
    hlen = rd32(p + 8);
    hoff = 12;
  }
  std::string header(p + hoff, hlen);
  // header is a python dict literal: {'descr': '<f4', 'fortran_order':
  // False, 'shape': (3, 4), }
  auto find_val = [&](const std::string& key) -> std::string {
    size_t k = header.find("'" + key + "'");
    if (k == std::string::npos) return "";
    size_t c = header.find(':', k);
    size_t start = header.find_first_not_of(" ", c + 1);
    size_t end = start;
    if (header[start] == '\'') {
      end = header.find('\'', start + 1) + 1;
    } else if (header[start] == '(') {
      end = header.find(')', start) + 1;
    } else {
      end = header.find_first_of(",}", start);
    }
    return header.substr(start, end - start);
  };
  if (find_val("fortran_order") != "False") {
    set_error("fortran_order arrays unsupported");
    return false;
  }
  std::string descr = find_val("descr");
  out->dtype = descr.substr(1, descr.size() - 2);  // strip quotes
  if (!out->dtype.empty() && (out->dtype[0] == '<' || out->dtype[0] == '>' ||
                              out->dtype[0] == '=' || out->dtype[0] == '|')) {
    if (out->dtype[0] == '>') {
      set_error("big-endian npy arrays unsupported");
      return false;
    }
    out->dtype = out->dtype.substr(1);
  }
  std::string shape = find_val("shape");           // "(3, 4)" or "()"
  out->shape.clear();
  for (size_t i = 1; i < shape.size();) {
    if (isdigit(shape[i])) {
      size_t j = i;
      while (j < shape.size() && isdigit(shape[j])) j++;
      out->shape.push_back(std::stoll(shape.substr(i, j - i)));
      i = j;
    } else {
      i++;
    }
  }
  out->data = p + hoff + hlen;
  out->nbytes = n - hoff - hlen;
  return true;
}

bool parse_npz(const std::string& buf, std::vector<NpyArray>* arrays) {
  // walk the central directory (local headers may use data descriptors, so
  // their size fields can be zero — numpy writes them that way)
  size_t eocd = std::string::npos;
  for (size_t i = buf.size() >= 22 ? buf.size() - 22 : 0; i + 4 <= buf.size();
       i--) {
    if (rd32(buf.data() + i) == 0x06054b50) {
      eocd = i;
      break;
    }
    if (i == 0) break;
  }
  if (eocd == std::string::npos) {
    set_error("npz: no zip end-of-central-directory record");
    return false;
  }
  uint16_t n_entries = rd16(buf.data() + eocd + 10);
  uint32_t cd_off = rd32(buf.data() + eocd + 16);
  if (cd_off == 0xFFFFFFFFu || n_entries == 0xFFFFu) {
    set_error("zip64 npz archives (>4GB or >65535 members) unsupported by "
              "the native predictor; shard the params");
    return false;
  }
  size_t off = cd_off;
  for (uint16_t e = 0; e < n_entries; e++) {
    if (off + 46 > buf.size() || rd32(buf.data() + off) != 0x02014b50) {
      set_error("npz: bad central directory entry");
      return false;
    }
    uint16_t method = rd16(buf.data() + off + 10);
    uint32_t csize = rd32(buf.data() + off + 20);
    uint16_t nlen = rd16(buf.data() + off + 28);
    uint16_t elen = rd16(buf.data() + off + 30);
    uint16_t clen = rd16(buf.data() + off + 32);
    uint32_t lho = rd32(buf.data() + off + 42);
    std::string name(buf.data() + off + 46, nlen);
    off += 46 + nlen + elen + clen;
    if (method != 0) {
      set_error("npz member " + name + " is compressed; expected "
                "np.savez (uncompressed)");
      return false;
    }
    // local header gives the true data offset (its name/extra lengths can
    // differ from the central entry's)
    uint16_t lh_nlen = rd16(buf.data() + lho + 26);
    uint16_t lh_elen = rd16(buf.data() + lho + 28);
    const char* data = buf.data() + lho + 30 + lh_nlen + lh_elen;
    NpyArray arr;
    if (!parse_npy(data, csize, &arr)) return false;
    if (name.size() > 4 && name.substr(name.size() - 4) == ".npy")
      name = name.substr(0, name.size() - 4);
    arr.name = name;
    arrays->push_back(arr);
  }
  if (arrays->empty()) {
    set_error("no npy members found in npz");
    return false;
  }
  return true;
}

// dtype descr -> PJRT type + element size
bool dtype_to_pjrt(const std::string& d, PJRT_Buffer_Type* t, size_t* size) {
  if (d == "f4") { *t = PJRT_Buffer_Type_F32; *size = 4; return true; }
  if (d == "f8") { *t = PJRT_Buffer_Type_F64; *size = 8; return true; }
  if (d == "f2") { *t = PJRT_Buffer_Type_F16; *size = 2; return true; }
  if (d == "i4") { *t = PJRT_Buffer_Type_S32; *size = 4; return true; }
  if (d == "i8") { *t = PJRT_Buffer_Type_S64; *size = 8; return true; }
  if (d == "i1") { *t = PJRT_Buffer_Type_S8;  *size = 1; return true; }
  if (d == "u1") { *t = PJRT_Buffer_Type_U8;  *size = 1; return true; }
  if (d == "u4") { *t = PJRT_Buffer_Type_U32; *size = 4; return true; }
  if (d == "u8") { *t = PJRT_Buffer_Type_U64; *size = 8; return true; }
  if (d == "b1") { *t = PJRT_Buffer_Type_PRED; *size = 1; return true; }
  if (d == "V2" || d == "bfloat16") {
    *t = PJRT_Buffer_Type_BF16; *size = 2; return true;
  }
  set_error("unsupported dtype descr " + d);
  return false;
}

// predictor.py dtype codes (keep in sync with inference/native.py)
bool code_to_pjrt(int code, PJRT_Buffer_Type* t, size_t* size) {
  switch (code) {
    case 0: *t = PJRT_Buffer_Type_F32; *size = 4; return true;
    case 1: *t = PJRT_Buffer_Type_F64; *size = 8; return true;
    case 2: *t = PJRT_Buffer_Type_S32; *size = 4; return true;
    case 3: *t = PJRT_Buffer_Type_S64; *size = 8; return true;
    case 4: *t = PJRT_Buffer_Type_BF16; *size = 2; return true;
    case 5: *t = PJRT_Buffer_Type_PRED; *size = 1; return true;
    case 6: *t = PJRT_Buffer_Type_U8; *size = 1; return true;
    case 7: *t = PJRT_Buffer_Type_S8; *size = 1; return true;
  }
  set_error("bad dtype code " + std::to_string(code));
  return false;
}

int pjrt_to_code(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_F32: return 0;
    case PJRT_Buffer_Type_F64: return 1;
    case PJRT_Buffer_Type_S32: return 2;
    case PJRT_Buffer_Type_S64: return 3;
    case PJRT_Buffer_Type_BF16: return 4;
    case PJRT_Buffer_Type_PRED: return 5;
    case PJRT_Buffer_Type_U8: return 6;
    case PJRT_Buffer_Type_S8: return 7;
    case PJRT_Buffer_Type_F16: return 8;
    case PJRT_Buffer_Type_U16: return 9;
    case PJRT_Buffer_Type_S16: return 10;
    case PJRT_Buffer_Type_U32: return 11;
    case PJRT_Buffer_Type_U64: return 12;
    default: return -1;
  }
}

// extract ["a", "b", ...] for a key from the tiny .pdmeta json we write
std::vector<std::string> json_string_array(const std::string& js,
                                           const std::string& key) {
  std::vector<std::string> out;
  size_t k = js.find("\"" + key + "\"");
  if (k == std::string::npos) return out;
  size_t lb = js.find('[', k);
  size_t rb = js.find(']', lb);
  size_t i = lb;
  while (true) {
    size_t q1 = js.find('"', i + 1);
    if (q1 == std::string::npos || q1 > rb) break;
    size_t q2 = js.find('"', q1 + 1);
    out.push_back(js.substr(q1 + 1, q2 - q1 - 1));
    i = q2;
  }
  return out;
}

// ------------------------------------------------------------- predictor

struct Predictor {
  void* dl = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  PJRT_Device* device = nullptr;
  PJRT_LoadedExecutable* exec = nullptr;
  size_t num_params = 0;
  size_t num_outputs = 0;
  std::vector<PJRT_Buffer*> param_bufs;   // uploaded once
  std::vector<PJRT_Buffer*> out_bufs;     // last run's outputs

  bool await_event(PJRT_Event* ev) {
    PJRT_Event_Await_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
    args.event = ev;
    PJRT_Error* err = api->PJRT_Event_Await(&args);
    PJRT_Event_Destroy_Args dargs;
    std::memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
    dargs.event = ev;
    api->PJRT_Event_Destroy(&dargs);
    if (err) {
      set_error("event await: " + pjrt_error_message(api, err));
      return false;
    }
    return true;
  }

  bool host_to_device(const void* data, PJRT_Buffer_Type type,
                      const int64_t* dims, size_t ndims, PJRT_Buffer** out) {
    PJRT_Client_BufferFromHostBuffer_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    args.client = client;
    args.data = data;
    args.type = type;
    args.dims = dims;
    args.num_dims = ndims;
    args.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    args.device = device;
    PJRT_CHECK(api, api->PJRT_Client_BufferFromHostBuffer(&args));
    if (!await_event(args.done_with_host_buffer)) return false;
    *out = args.buffer;
    return true;
  }

  void destroy_buffer(PJRT_Buffer* b) {
    if (!b) return;
    PJRT_Buffer_Destroy_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    args.buffer = b;
    api->PJRT_Buffer_Destroy(&args);
  }

  bool init(const std::string& model_path, const std::string& plugin_path,
            const std::string& options) {
    dl = dlopen(plugin_path.c_str(), RTLD_NOW | RTLD_LOCAL);
    if (!dl) {
      set_error(std::string("dlopen failed: ") + dlerror());
      return false;
    }
    using GetApiFn = const PJRT_Api* (*)();
    auto get_api = reinterpret_cast<GetApiFn>(dlsym(dl, "GetPjrtApi"));
    if (!get_api) {
      set_error("plugin has no GetPjrtApi symbol");
      return false;
    }
    api = get_api();

    PJRT_Plugin_Initialize_Args iargs;
    std::memset(&iargs, 0, sizeof(iargs));
    iargs.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    PJRT_CHECK(api, api->PJRT_Plugin_Initialize(&iargs));

    // create_options: "key=value;key=value" — integer-looking values map
    // to kInt64, everything else to kString (matches what jax's
    // register_plugin(options=...) passes for e.g. the libtpu / axon
    // plugins: topology, session_id, rank, ...)
    std::vector<std::pair<std::string, std::string>> kv;
    for (size_t i = 0; i < options.size();) {
      size_t semi = options.find(';', i);
      if (semi == std::string::npos) semi = options.size();
      std::string pair = options.substr(i, semi - i);
      size_t eq = pair.find('=');
      if (eq != std::string::npos)
        kv.emplace_back(pair.substr(0, eq), pair.substr(eq + 1));
      i = semi + 1;
    }
    std::vector<PJRT_NamedValue> named(kv.size());
    std::vector<int64_t> int_store(kv.size());
    for (size_t i = 0; i < kv.size(); i++) {
      PJRT_NamedValue& nv = named[i];
      std::memset(&nv, 0, sizeof(nv));
      nv.struct_size = PJRT_NamedValue_STRUCT_SIZE;
      nv.name = kv[i].first.c_str();
      nv.name_size = kv[i].first.size();
      const std::string& v = kv[i].second;
      size_t digits_from = (v.size() > 1 && v[0] == '-') ? 1 : 0;
      bool is_int = v.size() > digits_from &&
          v.find_first_not_of("0123456789", digits_from) ==
              std::string::npos;
      if (is_int) {
        try {
          int_store[i] = std::stoll(v);
        } catch (const std::exception&) {
          set_error("bad integer option value '" + v + "' for key '" +
                    kv[i].first + "'");
          return false;
        }
        nv.type = PJRT_NamedValue_kInt64;
        nv.int64_value = int_store[i];
        nv.value_size = 1;
      } else {
        nv.type = PJRT_NamedValue_kString;
        nv.string_value = v.c_str();
        nv.value_size = v.size();
      }
    }

    PJRT_Client_Create_Args cargs;
    std::memset(&cargs, 0, sizeof(cargs));
    cargs.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
    cargs.create_options = named.empty() ? nullptr : named.data();
    cargs.num_options = named.size();
    PJRT_CHECK(api, api->PJRT_Client_Create(&cargs));
    client = cargs.client;

    PJRT_Client_AddressableDevices_Args devargs;
    std::memset(&devargs, 0, sizeof(devargs));
    devargs.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
    devargs.client = client;
    PJRT_CHECK(api, api->PJRT_Client_AddressableDevices(&devargs));
    if (devargs.num_addressable_devices == 0) {
      set_error("plugin reports no addressable devices");
      return false;
    }
    device = devargs.addressable_devices[0];

    // program: StableHLO text written by jit.save
    std::string mlir;
    if (!read_file(model_path + ".pdstablehlo", &mlir)) return false;

    PJRT_Program program;
    std::memset(&program, 0, sizeof(program));
    program.struct_size = PJRT_Program_STRUCT_SIZE;
    program.code = mlir.data();
    program.code_size = mlir.size();
    program.format = "mlir";
    program.format_size = 4;

    // minimal CompileOptionsProto: executable_build_options(field 3) with
    // num_replicas(4)=1, num_partitions(5)=1
    static const char kOptions[] = {0x1a, 0x04, 0x20, 0x01, 0x28, 0x01};

    PJRT_Client_Compile_Args comp;
    std::memset(&comp, 0, sizeof(comp));
    comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
    comp.client = client;
    comp.program = &program;
    comp.compile_options = kOptions;
    comp.compile_options_size = sizeof(kOptions);
    PJRT_CHECK(api, api->PJRT_Client_Compile(&comp));
    exec = comp.executable;

    PJRT_LoadedExecutable_GetExecutable_Args ge;
    std::memset(&ge, 0, sizeof(ge));
    ge.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
    ge.loaded_executable = exec;
    PJRT_CHECK(api, api->PJRT_LoadedExecutable_GetExecutable(&ge));
    PJRT_Executable_NumOutputs_Args no;
    std::memset(&no, 0, sizeof(no));
    no.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
    no.executable = ge.executable;
    PJRT_CHECK(api, api->PJRT_Executable_NumOutputs(&no));
    num_outputs = no.num_outputs;

    // parameters: ordered by .pdmeta param_names, uploaded once
    std::string meta;
    if (!read_file(model_path + ".pdmeta", &meta)) return false;
    std::vector<std::string> names = json_string_array(meta, "param_names");

    std::string npz;
    if (!read_file(model_path + ".pdiparams.npz", &npz)) return false;
    params_archive_ = std::move(npz);  // buffers point into this
    std::vector<NpyArray> arrays;
    if (!parse_npz(params_archive_, &arrays)) return false;

    for (const auto& name : names) {
      const NpyArray* found = nullptr;
      for (const auto& a : arrays)
        if (a.name == name) { found = &a; break; }
      if (!found) {
        set_error("param " + name + " missing from npz");
        return false;
      }
      PJRT_Buffer_Type t;
      size_t esize;
      if (!dtype_to_pjrt(found->dtype, &t, &esize)) return false;
      PJRT_Buffer* buf = nullptr;
      if (!host_to_device(found->data, t, found->shape.data(),
                          found->shape.size(), &buf))
        return false;
      param_bufs.push_back(buf);
    }
    num_params = param_bufs.size();
    return true;
  }

  bool run(int num_inputs, void** in_data, const int64_t* in_dims_flat,
           const int* in_ndims, const int* in_dtypes) {
    for (auto* b : out_bufs) destroy_buffer(b);
    out_bufs.clear();

    std::vector<PJRT_Buffer*> input_bufs;
    size_t dim_off = 0;
    bool ok = true;
    for (int i = 0; i < num_inputs && ok; i++) {
      PJRT_Buffer_Type t;
      size_t esize;
      if (!code_to_pjrt(in_dtypes[i], &t, &esize)) { ok = false; break; }
      PJRT_Buffer* buf = nullptr;
      ok = host_to_device(in_data[i], t, in_dims_flat + dim_off,
                          in_ndims[i], &buf);
      dim_off += in_ndims[i];
      if (ok) input_bufs.push_back(buf);
    }

    if (ok) {
      std::vector<PJRT_Buffer*> all_args(param_bufs);
      all_args.insert(all_args.end(), input_bufs.begin(), input_bufs.end());
      PJRT_Buffer* const* arg_list = all_args.data();

      std::vector<PJRT_Buffer*> outs(num_outputs, nullptr);
      PJRT_Buffer** out_list = outs.data();
      PJRT_Event* done = nullptr;

      PJRT_ExecuteOptions opts;
      std::memset(&opts, 0, sizeof(opts));
      opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;
      // params must not be donated: they are reused across run() calls
      std::vector<int64_t> non_donatable(num_params);
      for (size_t i = 0; i < num_params; i++) non_donatable[i] = i;
      opts.non_donatable_input_indices = non_donatable.data();
      opts.num_non_donatable_input_indices = non_donatable.size();

      PJRT_LoadedExecutable_Execute_Args eargs;
      std::memset(&eargs, 0, sizeof(eargs));
      eargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
      eargs.executable = exec;
      eargs.options = &opts;
      eargs.argument_lists = &arg_list;
      eargs.num_devices = 1;
      eargs.num_args = all_args.size();
      eargs.output_lists = &out_list;
      eargs.device_complete_events = &done;
      PJRT_Error* err = api->PJRT_LoadedExecutable_Execute(&eargs);
      if (err) {
        set_error("execute: " + pjrt_error_message(api, err));
        ok = false;
      } else {
        ok = await_event(done);
        out_bufs.assign(outs.begin(), outs.end());
      }
    }
    for (auto* b : input_bufs) destroy_buffer(b);
    return ok;
  }

  bool output_info(int i, int64_t* dims, int max_dims, int* ndims,
                   int* dtype_code) {
    PJRT_Buffer* b = out_bufs.at(i);
    PJRT_Buffer_Dimensions_Args dargs;
    std::memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
    dargs.buffer = b;
    PJRT_CHECK(api, api->PJRT_Buffer_Dimensions(&dargs));
    if (dargs.num_dims > static_cast<size_t>(max_dims)) {
      set_error("output rank " + std::to_string(dargs.num_dims) +
                " exceeds caller capacity " + std::to_string(max_dims));
      return false;
    }
    *ndims = static_cast<int>(dargs.num_dims);
    for (size_t d = 0; d < dargs.num_dims; d++) dims[d] = dargs.dims[d];
    PJRT_Buffer_ElementType_Args targs;
    std::memset(&targs, 0, sizeof(targs));
    targs.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
    targs.buffer = b;
    PJRT_CHECK(api, api->PJRT_Buffer_ElementType(&targs));
    *dtype_code = pjrt_to_code(targs.type);
    if (*dtype_code < 0) {
      set_error("unsupported output element type " +
                std::to_string(static_cast<int>(targs.type)));
      return false;
    }
    return true;
  }

  bool output_copy(int i, void* dst, size_t dst_size) {
    PJRT_Buffer_ToHostBuffer_Args args;
    std::memset(&args, 0, sizeof(args));
    args.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    args.src = out_bufs.at(i);
    args.dst = dst;
    args.dst_size = dst_size;
    PJRT_CHECK(api, api->PJRT_Buffer_ToHostBuffer(&args));
    return await_event(args.event);
  }

  ~Predictor() {
    for (auto* b : out_bufs) destroy_buffer(b);
    if (!owner) return;  // clones share client/exec/params with the owner
    for (auto* b : param_bufs) destroy_buffer(b);
    if (exec) {
      PJRT_LoadedExecutable_Destroy_Args args;
      std::memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
      args.executable = exec;
      api->PJRT_LoadedExecutable_Destroy(&args);
    }
    if (client) {
      PJRT_Client_Destroy_Args args;
      std::memset(&args, 0, sizeof(args));
      args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
      args.client = client;
      api->PJRT_Client_Destroy(&args);
    }
    // the plugin .so stays loaded (unloading PJRT plugins is unsafe)
  }

  std::string params_archive_;
  bool owner = true;
};

}  // namespace

// ----------------------------------------------------------------- C ABI

extern "C" {

const char* pd_predictor_last_error() { return g_last_error.c_str(); }

void* pd_predictor_create(const char* model_path, const char* plugin_path,
                          const char* options) {
  auto p = std::make_unique<Predictor>();
  if (!p->init(model_path, plugin_path, options ? options : ""))
    return nullptr;
  return p.release();
}

int pd_predictor_num_outputs(void* h) {
  return static_cast<int>(static_cast<Predictor*>(h)->num_outputs);
}

int pd_predictor_run(void* h, int num_inputs, void** in_data,
                     const int64_t* in_dims_flat, const int* in_ndims,
                     const int* in_dtypes) {
  return static_cast<Predictor*>(h)->run(num_inputs, in_data, in_dims_flat,
                                         in_ndims, in_dtypes)
             ? 0
             : -1;
}

int pd_predictor_output_info(void* h, int i, int64_t* dims, int max_dims,
                             int* ndims, int* dtype_code) {
  return static_cast<Predictor*>(h)->output_info(i, dims, max_dims, ndims,
                                                 dtype_code)
             ? 0
             : -1;
}

int pd_predictor_output_copy(void* h, int i, void* dst, int64_t dst_size) {
  return static_cast<Predictor*>(h)->output_copy(
             i, dst, static_cast<size_t>(dst_size))
             ? 0
             : -1;
}

void pd_predictor_destroy(void* h) { delete static_cast<Predictor*>(h); }

// Pool support (reference PredictorPool: clone the program, share the
// weights): a clone shares the PJRT client, the compiled executable, and
// the device-resident parameters with the owner, but keeps its OWN output
// buffers, so concurrent requests on different clones never race on
// results.  The owner must outlive its clones.
void* pd_predictor_clone(void* h) {
  auto* src = static_cast<Predictor*>(h);
  auto p = std::make_unique<Predictor>();
  p->dl = src->dl;
  p->api = src->api;
  p->client = src->client;
  p->device = src->device;
  p->exec = src->exec;
  p->num_params = src->num_params;
  p->num_outputs = src->num_outputs;
  p->param_bufs = src->param_bufs;
  p->owner = false;
  return p.release();
}

}  // extern "C"
