"""Multi-controller execution proof (VERDICT r3 Missing #3).

Spawns 2 REAL processes through the launch CLI; they barrier on the native
TCPStore, rendezvous via ``distributed.env.init_parallel_env`` →
``jax.distributed.initialize`` (gloo CPU collectives), run a DP train step
over the 4-device global mesh, and write a per-shard checkpoint.  The
parent asserts loss/grad parity against the identical single-process
computation and that the checkpoint really is per-process-sharded.

Reference pattern: test/legacy_test/test_parallel_dygraph_dataparallel.py
(N procs on one host, compare against serial run).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

# 2-process launch drills: wall time balloons on loaded CI
# cores (observed 5s..100s+). Tier-2: @slow, run unfiltered
# by the CI multi-process drill gate.
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mc_train_worker.py")


from paddle_tpu.distributed.elastic import free_port as _free_port  # noqa: E402


def _single_process_reference():
    """The worker's math, eagerly, in this (already-initialized) process."""
    import jax
    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    w1 = jnp.asarray(rs.randn(8, 16).astype(np.float32))
    w2 = jnp.asarray(rs.randn(16, 4).astype(np.float32))
    x = jnp.asarray(rs.randn(8, 8).astype(np.float32))
    y = jnp.asarray(rs.randint(0, 4, size=(8, 1)))

    def loss_fn(p, xb, yb):
        h = jnp.tanh(xb @ p["w1"])
        logits = h @ p["w2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, yb, axis=1))

    loss, grads = jax.value_and_grad(loss_fn)({"w1": w1, "w2": w2}, x, y)
    return float(loss), grads


def test_two_process_dp_parity(tmp_path):
    port = _free_port()
    store_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PADDLE_STORE_PORT"] = str(store_port)
    # scrub any leftover rendezvous env from the pytest process
    for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_MASTER"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "logs"), WORKER, str(tmp_path)],
        env=env, timeout=300, capture_output=True, text=True)
    logs = ""
    log_dir = tmp_path / "logs"
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-4000:]
    assert proc.returncode == 0, f"launcher rc={proc.returncode}\n{logs}"

    with open(tmp_path / "result.json") as f:
        result = json.load(f)
    assert result["world"] == 2
    assert result["devices"] == 4

    ref_loss, ref_grads = _single_process_reference()
    assert abs(result["loss"] - ref_loss) < 1e-5

    dumped = np.load(tmp_path / "grads.npz")
    np.testing.assert_allclose(dumped["w1"], np.asarray(ref_grads["w1"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dumped["w2"], np.asarray(ref_grads["w2"]),
                               rtol=1e-5, atol=1e-6)

    # checkpoint written cooperatively: one index per process, w1 split in
    # 4 dp shards of (2, 16) — no single file holds the global array
    ckpt = tmp_path / "ckpt"
    names = os.listdir(ckpt)
    assert "index.0.json" in names and "index.1.json" in names
    w1_shards = [n for n in names if n.startswith("w1") and ".shard." in n]
    assert len(w1_shards) == 4
    for n in w1_shards:
        assert np.load(ckpt / n).shape == (2, 16)

    import paddle_tpu.distributed as dist
    assert dist.validate_checkpoint(str(ckpt))
    loaded = dist.load_state_dict(str(ckpt))
    rs = np.random.RandomState(0)
    np.testing.assert_allclose(np.asarray(loaded["w1"]),
                               rs.randn(8, 16).astype(np.float32))
    assert int(loaded["step"]) == 1


FSDP_TP_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "mc_fsdp_tp_worker.py")
RESTORE_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "mc_restore_worker.py")


def _launch_workers(worker, tmp_path, n=2, extra_env=None):
    port = _free_port()
    store_port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PADDLE_STORE_PORT"] = str(store_port)
    env.update(extra_env or {})
    for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_MASTER"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", str(n), "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "logs"), worker, str(tmp_path)],
        env=env, timeout=300, capture_output=True, text=True)
    logs = ""
    log_dir = tmp_path / "logs"
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-4000:]
    assert proc.returncode == 0, f"launcher rc={proc.returncode}\n{logs}"


def _single_process_fsdp_tp_reference():
    """The fsdp+tp worker's TrainStep on 4 devices of THIS process."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu as pp
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    pp.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=32,
                           intermediate_size=64, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2)
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-2,
                             parameters=model.parameters())
    rules = LlamaForCausalLM.partition_specs(cfg, fsdp_axis="fsdp")
    specs = {n: LlamaForCausalLM.spec_for(n, rules)
             for n in model.state_dict(keep_vars=True)}
    step = TrainStep(model, opt, mesh=mesh, param_specs=specs,
                     batch_spec=P("fsdp"))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, size=(4, 17))
    loss = step({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
    emb_name = next(n for n in step.params if "embed" in n)
    proj_name = next(n for n in step.params if n.endswith("q_proj.weight"))
    repl = NamedSharding(mesh, P())
    return (float(loss),
            np.asarray(jax.device_put(step.params[emb_name], repl)),
            np.asarray(jax.device_put(step.params[proj_name], repl)))


def test_two_process_fsdp_tp_parity_and_restore_in_one(tmp_path):
    """(a) 2-proc x 4-device fsdp+tp TrainStep == single-process run;
    (b) the checkpoint saved under 2 processes restores in THIS single
    process through load_state_dict (VERDICT r4 Weak #3 / Next #5)."""
    _launch_workers(FSDP_TP_WORKER, tmp_path)

    with open(tmp_path / "result.json") as f:
        result = json.load(f)
    assert result["world"] == 2 and result["devices"] == 4

    ref_loss, ref_emb, ref_proj = _single_process_fsdp_tp_reference()
    assert abs(result["loss"] - ref_loss) < 1e-4, \
        (result["loss"], ref_loss)
    dumped = np.load(tmp_path / "params.npz")
    np.testing.assert_allclose(dumped["emb"], ref_emb, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(dumped["proj"], ref_proj, rtol=1e-5,
                               atol=1e-6)

    # (b) save@2proc -> restore@1proc: the parent is a plain single
    # process; load_state_dict assembles the global tensors from the
    # per-process shard files
    import paddle_tpu.distributed as dist
    ckpt = str(tmp_path / "ckpt")
    names = os.listdir(ckpt)
    assert "index.0.json" in names and "index.1.json" in names
    assert dist.validate_checkpoint(ckpt)
    loaded = dist.load_state_dict(ckpt)
    np.testing.assert_allclose(np.asarray(loaded[result["emb_name"]]),
                               dumped["emb"], rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(loaded[result["proj_name"]]),
                               dumped["proj"], rtol=1e-6, atol=1e-7)


def test_save_one_process_restore_two(tmp_path):
    """save@1proc -> restore@2proc: this process saves fsdp+tp-sharded
    state on its local 4-device mesh; 2 launched processes rebuild it on
    a 2-process global mesh via load_state_dict(mesh, specs)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu.distributed as dist

    rs = np.random.RandomState(3)
    a = rs.randn(8, 8).astype(np.float32)
    b = rs.randn(4, 6).astype(np.float32)
    np.savez(tmp_path / "expected.npz", a=a, b=b)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("fsdp", "tp"))
    sd = {
        "a": jax.device_put(a, NamedSharding(mesh, P("fsdp", "tp"))),
        "b": jax.device_put(b, NamedSharding(mesh, P("tp", None))),
        "step": 7,
    }
    dist.save_state_dict(sd, str(tmp_path / "ckpt_1proc"))
    assert dist.validate_checkpoint(str(tmp_path / "ckpt_1proc"))

    _launch_workers(RESTORE_WORKER, tmp_path)
    with open(tmp_path / "restore_ok.json") as f:
        out = json.load(f)
    assert out["ok"] and out["world"] == 2
