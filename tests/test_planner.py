"""Flagship-config capacity proof (VERDICT r2 item 2): AOT-compile the
REAL Llama-3-8B / 70B 4-D programs and a DeepSeekMoE program on a virtual
64-device CPU mesh and assert the per-device memory from XLA's buffer
assignment fits v5p HBM (95 GiB).

The 64-device runs happen in subprocesses because the virtual device count
is fixed at first jax init (this suite runs on 8).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestTinyPlanInProcess:
    def test_llama_plan_reports_memory(self):
        from paddle_tpu.distributed.planner import DenseConfig, plan_llama
        tiny = DenseConfig("tiny", vocab=512, d=64, ffn=128, layers=4,
                           heads=4, kv_heads=2)
        rep = plan_llama(tiny, pp=2, dp=2, fsdp=2, tp=1, seq=64,
                         mb_size=2, num_microbatches=4)
        assert rep.n_devices == 8
        assert rep.peak_bytes_per_device > 0
        assert rep.resident_bytes > 0
        # bf16 params + fp32 master+m+v, pp+fsdp sharded: arguments must
        # be at least the resident param bytes per device
        per_dev_param_bytes = rep.params_total * 2 / 8
        assert rep.resident_bytes > per_dev_param_bytes
        assert rep.fits(hbm_gb=8.0)
        assert "tiny" in rep.summary()

    def test_moe_plan_reports_memory(self):
        from paddle_tpu.distributed.planner import MoEConfig, plan_moe
        tiny = MoEConfig("tinymoe", vocab=512, d=64, layers=2, heads=4,
                         n_experts=8, n_shared=1, top_k=2, expert_ffn=32)
        rep = plan_moe(tiny, dp=1, fsdp=2, ep=4, tp=1, seq=64, batch=4)
        assert rep.n_devices == 8
        assert rep.peak_bytes_per_device > 0
        assert rep.fits(hbm_gb=8.0)


def _run_plan_subprocess(code: str) -> dict:
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=64"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", _PRELUDE + textwrap.dedent(code)], env=env,
        capture_output=True, text=True, timeout=3000)
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


_PRELUDE = """
import jax
jax.config.update("jax_platforms", "cpu")
import json
"""


@pytest.mark.slow
class TestFlagshipConfigsFitV5p:
    """The BASELINE.md config matrix, compiled at full size on 64 virtual
    devices; per-device peak must fit a v5p chip (95 GiB HBM)."""

    def test_llama3_8b_4d_fits(self):
        rep = _run_plan_subprocess("""
        from paddle_tpu.distributed.planner import plan_llama, LLAMA3_8B
        rep = plan_llama(LLAMA3_8B, pp=4, dp=2, fsdp=8, tp=1, seq=8192,
                         mb_size=1)
        print(rep.summary())
        print(json.dumps({"fits": rep.fits(95.0), "peak": rep.peak_bytes_per_device,
                          "resident": rep.resident_bytes,
                          "params": rep.params_total}))
        """)
        assert rep["fits"], rep
        assert 7.5e9 < rep["params"] < 8.5e9, rep["params"]
        # resident args must at least hold the ZeRO-sharded state
        assert rep["resident"] > rep["params"] * 14 / 64

    def test_llama3_70b_4d_fits(self):
        rep = _run_plan_subprocess("""
        from paddle_tpu.distributed.planner import plan_llama, LLAMA3_70B
        rep = plan_llama(LLAMA3_70B, pp=4, dp=1, fsdp=8, tp=2, seq=8192,
                         mb_size=1, scatter_grads_per_tick=True)
        print(rep.summary())
        print(json.dumps({"fits": rep.fits(95.0), "peak": rep.peak_bytes_per_device,
                          "params": rep.params_total}))
        """)
        assert rep["fits"], rep
        assert 6.5e10 < rep["params"] < 7.5e10, rep["params"]

    def test_deepseek_moe_fits(self):
        rep = _run_plan_subprocess("""
        from paddle_tpu.distributed.planner import plan_moe, DEEPSEEK_MOE_16B
        rep = plan_moe(DEEPSEEK_MOE_16B, dp=2, fsdp=4, ep=8, tp=1,
                       seq=4096, batch=8)
        print(rep.summary())
        print(json.dumps({"fits": rep.fits(95.0), "peak": rep.peak_bytes_per_device,
                          "params": rep.params_total}))
        """)
        assert rep["fits"], rep
        assert 1.2e10 < rep["params"] < 2.0e10, rep["params"]

    def test_ernie45_moe_fits(self):
        """ERNIE-4.5-21B-A3B (models/ernie.py) AOT-planned on the virtual
        64-mesh — the BASELINE config family with zero representation in
        round 3 (VERDICT Missing #1)."""
        rep = _run_plan_subprocess("""
        from paddle_tpu.distributed.planner import plan_moe, ERNIE45_21B_A3B
        rep = plan_moe(ERNIE45_21B_A3B, dp=2, fsdp=4, ep=8, tp=1,
                       seq=4096, batch=8)
        print(rep.summary())
        print(json.dumps({"fits": rep.fits(95.0), "peak": rep.peak_bytes_per_device,
                          "params": rep.params_total}))
        """)
        assert rep["fits"], rep
        # ~21B total parameters
        assert 1.7e10 < rep["params"] < 2.6e10, rep["params"]
