"""paddle.geometric, paddle.audio, and compiled generation tests.

Oracles: numpy segment reductions, scipy-free closed forms for mel math,
and full-forward (cache-free) greedy decoding for generate().
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pp


class TestGeometric:
    def test_segment_ops(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        ids = np.array([0, 0, 1, 1])
        np.testing.assert_allclose(
            np.asarray(pp.geometric.segment_sum(jnp.asarray(x),
                                                jnp.asarray(ids))),
            np.stack([x[:2].sum(0), x[2:].sum(0)]))
        np.testing.assert_allclose(
            np.asarray(pp.geometric.segment_mean(jnp.asarray(x),
                                                 jnp.asarray(ids))),
            np.stack([x[:2].mean(0), x[2:].mean(0)]))
        np.testing.assert_allclose(
            np.asarray(pp.geometric.segment_max(jnp.asarray(x),
                                                jnp.asarray(ids))),
            np.stack([x[:2].max(0), x[2:].max(0)]))
        np.testing.assert_allclose(
            np.asarray(pp.geometric.segment_min(jnp.asarray(x),
                                                jnp.asarray(ids))),
            np.stack([x[:2].min(0), x[2:].min(0)]))

    def test_send_u_recv(self):
        x = np.eye(3, dtype=np.float32)
        src = np.array([0, 1, 2, 2])
        dst = np.array([1, 0, 0, 1])
        out = np.asarray(pp.geometric.send_u_recv(
            jnp.asarray(x), jnp.asarray(src), jnp.asarray(dst), "sum"))
        want = np.zeros((3, 3), np.float32)
        for s, d in zip(src, dst):
            want[d] += x[s]
        np.testing.assert_allclose(out, want)
        # mean / max reduce
        out_m = np.asarray(pp.geometric.send_u_recv(
            jnp.asarray(x), jnp.asarray(src), jnp.asarray(dst), "mean"))
        np.testing.assert_allclose(out_m[0], want[0] / 2)
        out_mx = np.asarray(pp.geometric.send_u_recv(
            jnp.asarray(x), jnp.asarray(src), jnp.asarray(dst), "max"))
        assert out_mx[2].sum() == 0  # untouched row zeroed, not -inf

    def test_send_ue_recv_and_uv(self):
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        e = np.ones((4, 2), np.float32)
        src = np.array([0, 1, 2, 0])
        dst = np.array([1, 2, 0, 2])
        out = np.asarray(pp.geometric.send_ue_recv(
            jnp.asarray(x), jnp.asarray(e), jnp.asarray(src),
            jnp.asarray(dst), "add", "sum"))
        want = np.zeros((3, 2), np.float32)
        for i, (s, d) in enumerate(zip(src, dst)):
            want[d] += x[s] + e[i]
        np.testing.assert_allclose(out, want)
        uv = np.asarray(pp.geometric.send_uv(
            jnp.asarray(x), jnp.asarray(x), jnp.asarray(src),
            jnp.asarray(dst), "mul"))
        np.testing.assert_allclose(uv, x[src] * x[dst])

    def test_grads_flow(self):
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(4, 3)).astype(np.float32))
        ids = jnp.asarray(np.array([0, 1, 0, 1]))
        g = jax.grad(lambda v: (pp.geometric.segment_sum(v, ids) ** 2)
                     .sum())(x)
        assert np.isfinite(np.asarray(g)).all()


class TestAudio:
    def test_mel_roundtrip_and_monotone(self):
        AF = pp.audio.functional
        for htk in (False, True):
            for hz in (110.0, 440.0, 4000.0):
                back = AF.mel_to_hz(AF.hz_to_mel(hz, htk), htk)
                np.testing.assert_allclose(back, hz, rtol=1e-4)
        freqs = np.asarray(AF.mel_frequencies(10, 0, 8000)._data)
        assert (np.diff(freqs) > 0).all()

    def test_fbank_properties(self):
        fb = np.asarray(pp.audio.functional.compute_fbank_matrix(
            16000, 512, n_mels=26)._data)
        assert fb.shape == (26, 257)
        assert (fb >= 0).all()
        assert (fb.sum(axis=1) > 0).all()

    def test_spectrogram_peak(self):
        sr = 8000
        t = np.arange(sr, dtype=np.float32) / sr
        sig = np.sin(2 * np.pi * 1000 * t)[None]
        spec = pp.audio.features.Spectrogram(n_fft=256, hop_length=128)(
            pp.to_tensor(sig))
        mag = np.asarray(spec._data)[0].mean(-1)
        peak_hz = mag.argmax() * sr / 256
        assert abs(peak_hz - 1000) < sr / 256  # within one bin

    def test_mfcc_shapes_and_dct_orthonormal(self):
        mf = pp.audio.features.MFCC(sr=8000, n_mfcc=13, n_fft=256,
                                    n_mels=26)
        sig = np.random.default_rng(0).normal(size=(2, 4000)) \
            .astype(np.float32)
        out = np.asarray(mf(pp.to_tensor(sig))._data)
        assert out.shape[0] == 2 and out.shape[1] == 13
        dct = np.asarray(pp.audio.functional.create_dct(13, 26)._data)
        gram = dct.T @ dct
        np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)

    def test_mel_pipeline_backprops(self):
        # the audio front-end must stay on the tape end-to-end
        mel = pp.audio.features.MelSpectrogram(sr=8000, n_fft=128,
                                               n_mels=8)
        sig = pp.to_tensor(np.random.default_rng(0).normal(
            size=(1, 1000)).astype(np.float32), stop_gradient=False)
        out = mel(sig)
        assert not out.stop_gradient
        out.sum().backward()
        assert sig.grad is not None
        assert np.isfinite(np.asarray(sig.grad._data)).all()

    def test_power_to_db(self):
        x = np.array([1.0, 10.0, 100.0], np.float32)
        db = np.asarray(pp.audio.functional.power_to_db(
            jnp.asarray(x), top_db=None))
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


class TestGenerate:
    def _model(self):
        pp.seed(0)
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        return LlamaForCausalLM(LlamaConfig.tiny())

    @pytest.mark.slow
    def test_greedy_matches_full_forward(self):
        from paddle_tpu.generation import GenerationConfig
        model = self._model()
        prompt = np.array([[1, 5, 9, 3], [2, 7, 4, 8]], np.int32)
        out = model.generate(prompt, GenerationConfig(max_new_tokens=5))
        ids = prompt.copy()
        for _ in range(5):
            logits = model(pp.to_tensor(ids))
            nxt = np.asarray(logits._data)[:, -1].argmax(-1) \
                .astype(np.int32)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, ids)

    def test_eos_padding(self):
        from paddle_tpu.generation import GenerationConfig
        model = self._model()
        prompt = np.array([[1, 5]], np.int32)
        # first greedy token becomes the "eos" -> everything after is pad
        first = model.generate(prompt,
                               GenerationConfig(max_new_tokens=1))[0, -1]
        out = model.generate(prompt, GenerationConfig(
            max_new_tokens=4, eos_token_id=int(first), pad_token_id=0))
        assert (out[0, 3:] == 0).all()

    def test_sampling_reproducible_and_varied(self):
        from paddle_tpu.generation import GenerationConfig
        model = self._model()
        prompt = np.array([[1, 5, 9]], np.int32)
        cfg = GenerationConfig(max_new_tokens=6, do_sample=True,
                               temperature=1.0, top_p=0.95, seed=7)
        a = model.generate(prompt, cfg)
        b = model.generate(prompt, cfg)
        np.testing.assert_array_equal(a, b)  # same seed, same draw
        cfg2 = GenerationConfig(max_new_tokens=6, do_sample=True, seed=8)
        c = model.generate(prompt, cfg2)
        assert a.shape == c.shape

    def test_top_k_limits_support(self):
        from paddle_tpu.generation import _sample, GenerationConfig
        logits = jnp.asarray(
            np.array([[0., 1., 2., 3., 4.]], np.float32))
        cfg = GenerationConfig(do_sample=True, top_k=2, temperature=1.0)
        draws = {int(_sample(logits, cfg, jax.random.PRNGKey(i))[0])
                 for i in range(30)}
        assert draws <= {3, 4}

    def test_top_k_exceeding_vocab_is_clamped(self):
        from paddle_tpu.generation import _sample, GenerationConfig
        logits = jnp.asarray(np.array([[0., 1., 2.]], np.float32))
        cfg = GenerationConfig(do_sample=True, top_k=50, temperature=1.0)
        tok = _sample(logits, cfg, jax.random.PRNGKey(0))  # must not raise
        assert 0 <= int(tok[0]) < 3


class TestGPTGenerate:
    @pytest.mark.slow
    def test_gpt_greedy_matches_full_forward(self):
        pp.seed(0)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.generation import GenerationConfig
        m = GPTForCausalLM(GPTConfig.tiny())
        m.eval()  # dropout off: decode must be deterministic
        prompt = np.array([[1, 5, 9], [2, 4, 6]], np.int32)
        out = m.generate(prompt, GenerationConfig(max_new_tokens=4))
        ids = prompt.copy()
        for _ in range(4):
            logits = m(pp.to_tensor(ids))
            nxt = np.asarray(logits._data)[:, -1].argmax(-1) \
                .astype(np.int32)
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, ids)


class TestSummaryFlops:
    def test_summary_counts(self):
        net = pp.nn.Sequential(pp.nn.Linear(16, 32), pp.nn.ReLU(),
                               pp.nn.Linear(32, 4))
        info = pp.summary(net)
        assert info["total_params"] == 16 * 32 + 32 + 32 * 4 + 4
        assert info["trainable_params"] == info["total_params"]

    def test_flops_from_xla_cost(self):
        net = pp.nn.Sequential(pp.nn.Linear(16, 32), pp.nn.ReLU(),
                               pp.nn.Linear(32, 4))
        n = pp.flops(net, [1, 16])
        # 2*(16*32 + 32*4) matmul flops plus bias/relu epsilon
        assert 1000 < n < 2500


class TestAudioDatasets:
    """paddle.audio.datasets parity (reference esc50.py/tess.py) over the
    synthetic backend (same stance as vision/text datasets)."""

    def test_esc50_shapes_and_split_sizes(self):
        from paddle_tpu.audio.datasets import ESC50
        train = ESC50(mode="train", split=1)
        dev = ESC50(mode="dev", split=1)
        assert len(train) == 4 * 100 and len(dev) == 100
        wave, label = train[3]
        assert wave.shape == (int(44100 * 5.0),)
        assert wave.dtype == np.float32
        assert 0 <= int(label) < 50

    def test_esc50_deterministic(self):
        from paddle_tpu.audio.datasets import ESC50
        a, _ = ESC50(mode="dev")[5]
        b, _ = ESC50(mode="dev")[5]
        np.testing.assert_array_equal(a, b)

    def test_tess_feature_modes(self):
        from paddle_tpu.audio.datasets import TESS
        ds = TESS(mode="dev", feat_type="mfcc", n_mfcc=13)
        feat, label = ds[0]
        assert feat.shape[0] == 13
        assert 0 <= int(label) < 7
        mel = TESS(mode="dev", feat_type="melspectrogram", n_mels=32)[0][0]
        assert mel.shape[0] == 32

    def test_classes_separable_by_fundamental(self):
        """Different labels produce spectrally distinct waveforms."""
        from paddle_tpu.audio.datasets import TESS
        ds = TESS(mode="dev")
        w0, l0 = ds[0]
        w1, l1 = ds[1]
        assert int(l0) != int(l1)
        s0 = np.abs(np.fft.rfft(w0[:4096]))
        s1 = np.abs(np.fft.rfft(w1[:4096]))
        assert np.argmax(s0) != np.argmax(s1)

    def test_dataloader_integration(self):
        from paddle_tpu.audio.datasets import TESS
        from paddle_tpu.io import DataLoader
        dl = DataLoader(TESS(mode="dev"), batch_size=4)
        waves, labels = next(iter(dl))
        assert waves.shape[0] == 4 and labels.shape == (4,)

    def test_real_archive_path_clear_error(self):
        from paddle_tpu.audio.datasets import ESC50
        with pytest.raises(NotImplementedError, match="zero-egress"):
            ESC50(data_path="/data/esc50")
        with pytest.raises(NotImplementedError, match="zero-egress"):
            ESC50(archive={"url": "x"})

    def test_train_dev_disjoint_and_split_rotates(self):
        from paddle_tpu.audio.datasets import TESS
        train = TESS(mode="train", split=1)
        dev = TESS(mode="dev", split=1)
        assert len(train) == 4 * 56 and len(dev) == 56
        # disjoint: no dev waveform appears in train
        d0, _ = dev[0]
        t_hash = {hash(train[i][0].tobytes()) for i in range(len(train))}
        assert hash(d0.tobytes()) not in t_hash
        # rotating split changes the held-out items
        d0_s2, _ = TESS(mode="dev", split=2)[0]
        assert hash(d0.tobytes()) != hash(d0_s2.tobytes())

    def test_bad_mode_rejected(self):
        from paddle_tpu.audio.datasets import ESC50
        with pytest.raises(ValueError, match="mode"):
            ESC50(mode="test")
