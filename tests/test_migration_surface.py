"""MIGRATION.md drift guard (VERDICT r4 Missing #4 / Next #9).

Every row of MIGRATION.md's "Same surface (drop-in)" table names a Paddle
surface this package claims to provide.  This test walks the claims and
exercises each one — import + a minimal call where cheap — so the table
cannot drift from the package: deleting or renaming a claimed surface
fails CI, and a new drop-in row must come with the code that backs it.
"""

from __future__ import annotations

import os
import re

import numpy as np
import pytest

import paddle_tpu as pp

_MIGRATION = os.path.join(os.path.dirname(__file__), os.pardir,
                          "MIGRATION.md")


def _dropin_rows():
    """Parse the 'Same surface (drop-in)' table rows out of MIGRATION.md."""
    with open(_MIGRATION) as f:
        text = f.read()
    section = text.split("## Same surface (drop-in)")[1].split("## ")[0]
    rows = []
    for line in section.splitlines():
        if line.startswith("|") and not set(line) <= set("|- "):
            cells = [c.strip() for c in line.strip("|").split("|")]
            if cells and cells[0] != "Paddle":
                rows.append(cells)
    return rows


def test_migration_table_parses():
    rows = _dropin_rows()
    assert len(rows) >= 18, f"drop-in table shrank to {len(rows)} rows"


# One executable probe per drop-in row.  Keys are regexes matched against
# the row's first (Paddle) cell; every row MUST match exactly one probe —
# adding a row without a probe fails test_every_dropin_row_has_a_probe.
def _probe_tensor_ctors():
    t = pp.to_tensor(np.ones((2, 2), np.float32))
    assert tuple(pp.randn([2, 3]).shape) == (2, 3)
    assert tuple(pp.arange(5).shape) == (5,)
    return t


def _probe_tensor_methods():
    t = pp.to_tensor(np.ones((2, 3), np.float32))
    t.stop_gradient = False
    (t * t).sum().backward()
    assert t.grad is not None
    assert tuple(t.T.shape) == (3, 2)
    assert tuple(t.reshape([3, 2]).shape) == (3, 2)
    return t


def _probe_nn():
    layer = pp.nn.Linear(4, 2)
    out = layer(pp.randn([3, 4]))
    assert tuple(out.shape) == (3, 2)
    assert callable(pp.nn.functional.relu)
    assert callable(pp.nn.functional.cross_entropy)


def _probe_optimizer():
    lin = pp.nn.Linear(2, 2)
    opt = pp.optimizer.AdamW(learning_rate=1e-3,
                             parameters=lin.parameters())
    sched = pp.optimizer.lr.CosineAnnealingDecay(learning_rate=0.1,
                                                 T_max=10)
    assert isinstance(sched.get_lr(), float)
    assert hasattr(opt, "step") and hasattr(opt, "clear_grad")


def _probe_amp():
    assert callable(pp.amp.auto_cast)
    scaler = pp.amp.GradScaler()
    assert hasattr(scaler, "scale")


def _probe_io():
    class DS(pp.io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.float32(i)

    dl = pp.io.DataLoader(DS(), batch_size=2)
    assert len(list(dl)) == 2
    assert callable(pp.io.get_worker_info)


def _probe_metric_hapi():
    m = pp.metric.Accuracy()
    assert hasattr(m, "update") and hasattr(m, "accumulate")
    assert hasattr(pp.Model, "fit")


def _probe_vision():
    assert callable(pp.vision.models.resnet18)
    assert hasattr(pp.vision.transforms, "Resize")
    assert callable(pp.vision.ops.nms)


def _probe_text_audio():
    import paddle_tpu.text as text
    import paddle_tpu.audio as audio
    assert hasattr(text, "Vocab")
    assert hasattr(audio, "datasets")


def _probe_distribution():
    d = pp.distribution.Normal(0.0, 1.0)
    s = d.sample([2])
    arr = s.numpy() if hasattr(s, "numpy") else np.asarray(s)
    assert np.isfinite(arr).all()


def _probe_sparse_geometric():
    import paddle_tpu.sparse as sparse
    assert hasattr(sparse, "SparseCooTensor")
    assert hasattr(sparse, "SparseCsrTensor")
    assert callable(sparse.matmul)
    import paddle_tpu.geometric as geo
    assert callable(geo.segment_sum)


def _probe_linalg_fft():
    x = pp.to_tensor(np.eye(3, dtype=np.float32))
    assert pp.linalg.norm(x) is not None
    assert pp.fft.fft(pp.to_tensor(np.ones(4, np.float32))) is not None


def _probe_rpc():
    import paddle_tpu.distributed.rpc as rpc
    assert hasattr(rpc, "init_rpc") and hasattr(rpc, "rpc_sync")


def _probe_onnx():
    import paddle_tpu.onnx as onnx
    assert callable(onnx.export)


def _probe_hub():
    assert callable(pp.hub.load) and callable(pp.hub.list)


def _probe_quantization():
    import paddle_tpu.quantization as q
    assert hasattr(q, "QAT") or hasattr(q, "QuantConfig")


def _probe_static():
    import paddle_tpu.static as st
    assert hasattr(st, "InputSpec")
    assert hasattr(st, "save_inference_model")
    assert hasattr(st, "nn")


def _probe_tensorarray():
    arr = pp.tensor_array_to_tensor if hasattr(
        pp, "tensor_array_to_tensor") else None
    from paddle_tpu.ops import array_ops
    a = array_ops.create_array("float32")
    array_ops.array_write(pp.to_tensor(np.ones(2, np.float32)), 0, a)
    assert array_ops.array_length(a) == 1


_PROBES = [
    (r"to_tensor / randn", _probe_tensor_ctors),
    (r"`Tensor` methods", _probe_tensor_methods),
    (r"paddle\.nn", _probe_nn),
    (r"paddle\.optimizer", _probe_optimizer),
    (r"paddle\.amp", _probe_amp),
    (r"paddle\.io", _probe_io),
    (r"paddle\.metric", _probe_metric_hapi),
    (r"paddle\.vision", _probe_vision),
    (r"paddle\.text", _probe_text_audio),
    (r"paddle\.distribution", _probe_distribution),
    (r"paddle\.sparse", _probe_sparse_geometric),
    (r"paddle\.linalg", _probe_linalg_fft),
    (r"distributed\.rpc", _probe_rpc),
    (r"paddle\.onnx", _probe_onnx),
    (r"paddle\.hub", _probe_hub),
    (r"paddle\.quantization", _probe_quantization),
    (r"paddle\.static", _probe_static),
    (r"TensorArray", _probe_tensorarray),
]


def test_every_dropin_row_has_a_probe():
    rows = _dropin_rows()
    unmatched = []
    for cells in rows:
        if not any(re.search(pat, cells[0]) for pat, _ in _PROBES):
            unmatched.append(cells[0])
    assert not unmatched, (
        f"MIGRATION.md drop-in rows with no executable probe: {unmatched} "
        "— add a probe to tests/test_migration_surface.py for each")


@pytest.mark.parametrize("pat,probe", _PROBES,
                         ids=[p[0].replace("\\", "") for p in _PROBES])
def test_dropin_surface(pat, probe):
    """The claimed surface exists and minimally works."""
    probe()
