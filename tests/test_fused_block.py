"""Transformer-block megakernels + persistent autotune cache (ISSUE 8).

Covers: fused rmsnorm+QKV and fused (SwiGLU) MLP Pallas kernels —
interpret-mode fwd/bwd numerics vs the unfused reference at fp32 and
bf16 tolerances, the jaxpr cost-model assertions that each fused kernel
accesses strictly fewer HBM bytes than the unfused lowering on llama
block shapes, the PADDLE_TPU_FUSED_BLOCK routing (knob off restores the
previous path exactly; ineligible shapes fall back), the autoshard
checker round-trip of the fused model on the 8-device harness, and the
autotune cache v2 (versioned schema, corrupt-file tolerance, backend
key separation, hit/miss counters, offline dry-run sweep persistence).

Everything runs interpret-mode on CPU (conftest pins JAX_PLATFORMS).
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.core.dispatch import unwrap  # noqa: E402
from paddle_tpu.ops.pallas import autotune as at  # noqa: E402
from paddle_tpu.ops.pallas import fused_block as FB  # noqa: E402

EPS = 1e-5


def _qkv_ref(x, wn, wq, wk, wv):
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + EPS)
    xn = ((xf * inv) * wn.astype(jnp.float32)).astype(x.dtype)
    return xn @ wq, xn @ wk, xn @ wv


def _mlp_ref(x, wg, wu, wd):
    xf = x.astype(jnp.float32)
    h = (jax.nn.silu(xf @ wg.astype(jnp.float32)) *
         (xf @ wu.astype(jnp.float32))).astype(x.dtype)
    return (h.astype(jnp.float32) @ wd.astype(jnp.float32)).astype(x.dtype)


def _qkv_weights(rng, d, dq, dkv, dtype=jnp.float32):
    return (jnp.asarray(rng.standard_normal((d,)), dtype),
            jnp.asarray(rng.standard_normal((d, dq)) * 0.05, dtype),
            jnp.asarray(rng.standard_normal((d, dkv)) * 0.05, dtype),
            jnp.asarray(rng.standard_normal((d, dkv)) * 0.05, dtype))


# ---------------------------------------------------------------------------
# fused rmsnorm + QKV kernel
# ---------------------------------------------------------------------------

class TestFusedRmsnormQKV:
    def test_fwd_matches_reference(self):
        rng = np.random.default_rng(0)
        for t, d, dq, dkv in [(64, 128, 256, 128), (24, 128, 128, 128),
                              (128, 256, 256, 256)]:
            x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
            wn, wq, wk, wv = _qkv_weights(rng, d, dq, dkv)
            q, k, v = FB.fused_rmsnorm_qkv(x, wn, wq, wk, wv, epsilon=EPS)
            qr, kr, vr = _qkv_ref(x, wn, wq, wk, wv)
            for a, b in zip((q, k, v), (qr, kr, vr)):
                assert float(jnp.abs(a - b).max()) < 1e-5

    def test_leading_dims_preserved(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 16, 128)), jnp.float32)
        wn, wq, wk, wv = _qkv_weights(rng, 128, 256, 128)
        q, k, v = FB.fused_rmsnorm_qkv(x, wn, wq, wk, wv)
        assert q.shape == (2, 16, 256)
        assert k.shape == v.shape == (2, 16, 128)

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                           (jnp.bfloat16, 3e-2)])
    def test_grads_match_reference(self, dtype, tol):
        rng = np.random.default_rng(2)
        t, d, dq, dkv = 64, 128, 256, 128
        x = jnp.asarray(rng.standard_normal((t, d)), dtype)
        wn, wq, wk, wv = _qkv_weights(rng, d, dq, dkv, dtype)
        cq = jnp.asarray(rng.standard_normal((t, dq)), jnp.float32)
        ck = jnp.asarray(rng.standard_normal((t, dkv)), jnp.float32)

        def loss_fused(x, wn, wq, wk, wv):
            q, k, v = FB.fused_rmsnorm_qkv(x, wn, wq, wk, wv, epsilon=EPS)
            return (jnp.sum(q.astype(jnp.float32) * cq)
                    + jnp.sum(k.astype(jnp.float32) * ck)
                    + jnp.sum(v.astype(jnp.float32) ** 2))

        def loss_ref(x, wn, wq, wk, wv):
            q, k, v = _qkv_ref(x, wn, wq, wk, wv)
            return (jnp.sum(q.astype(jnp.float32) * cq)
                    + jnp.sum(k.astype(jnp.float32) * ck)
                    + jnp.sum(v.astype(jnp.float32) ** 2))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, wn, wq, wk, wv)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, wn, wq, wk, wv)
        for a, b in zip(gf, gr):
            scale = max(float(jnp.abs(b.astype(jnp.float32)).max()), 1e-6)
            err = float(jnp.abs(a.astype(jnp.float32)
                                - b.astype(jnp.float32)).max()) / scale
            assert err < tol, (a.shape, err)

    def test_ineligible_shape_falls_back_correctly(self):
        rng = np.random.default_rng(3)
        # d = 96 is not lane-tileable: reference math, same API
        x = jnp.asarray(rng.standard_normal((10, 96)), jnp.float32)
        wn = jnp.ones((96,), jnp.float32)
        w = jnp.asarray(rng.standard_normal((96, 96)), jnp.float32)
        q, k, v = FB.fused_rmsnorm_qkv(x, wn, w, w, w)
        jaxpr = str(jax.make_jaxpr(
            lambda a: FB.fused_rmsnorm_qkv(a, wn, w, w, w))(x))
        assert "pallas_call" not in jaxpr
        qr, _, _ = _qkv_ref(x, wn, w, w, w)
        assert float(jnp.abs(q - qr).max()) < 1e-5

    def test_bad_explicit_blocks_raise(self):
        x = jnp.zeros((64, 128), jnp.float32)
        wn = jnp.ones((128,), jnp.float32)
        w = jnp.zeros((128, 128), jnp.float32)
        with pytest.raises(ValueError, match="not divisible"):
            FB.fused_rmsnorm_qkv(x, wn, w, w, w, block_t=48, block_o=128)


# ---------------------------------------------------------------------------
# fused MLP / FFN kernels
# ---------------------------------------------------------------------------

class TestFusedMLP:
    def test_fwd_matches_reference(self):
        rng = np.random.default_rng(4)
        for t, d, f in [(64, 128, 512), (32, 128, 128), (128, 256, 384)]:
            x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
            wg = jnp.asarray(rng.standard_normal((d, f)) * 0.05, jnp.float32)
            wu = jnp.asarray(rng.standard_normal((d, f)) * 0.05, jnp.float32)
            wd = jnp.asarray(rng.standard_normal((f, d)) * 0.05, jnp.float32)
            y = FB.fused_mlp(x, wg, wu, wd)
            yr = _mlp_ref(x, wg, wu, wd)
            scale = max(float(jnp.abs(yr).max()), 1e-6)
            assert float(jnp.abs(y - yr).max()) / scale < 1e-5

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                           (jnp.bfloat16, 3e-2)])
    def test_grads_match_reference(self, dtype, tol):
        rng = np.random.default_rng(5)
        t, d, f = 64, 128, 384
        x = jnp.asarray(rng.standard_normal((t, d)), dtype)
        wg = jnp.asarray(rng.standard_normal((d, f)) * 0.05, dtype)
        wu = jnp.asarray(rng.standard_normal((d, f)) * 0.05, dtype)
        wd = jnp.asarray(rng.standard_normal((f, d)) * 0.05, dtype)

        def lf(*a):
            return jnp.sum(FB.fused_mlp(*a).astype(jnp.float32) ** 2)

        def lr(*a):
            return jnp.sum(_mlp_ref(*a).astype(jnp.float32) ** 2)

        gf = jax.grad(lf, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        gr = jax.grad(lr, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
        for a, b in zip(gf, gr):
            scale = max(float(jnp.abs(b.astype(jnp.float32)).max()), 1e-6)
            err = float(jnp.abs(a.astype(jnp.float32)
                                - b.astype(jnp.float32)).max()) / scale
            assert err < tol, (a.shape, err)

    @pytest.mark.parametrize("act", ["relu", "gelu", "silu"])
    @pytest.mark.parametrize("bias", [True, False])
    def test_ffn_acts_and_bias(self, act, bias):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(6)
        t, d, f = 32, 128, 256
        x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32)
        w1 = jnp.asarray(rng.standard_normal((d, f)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.standard_normal((f, d)) * 0.1, jnp.float32)
        b1 = jnp.asarray(rng.standard_normal((f,)), jnp.float32) \
            if bias else None
        b2 = jnp.asarray(rng.standard_normal((d,)), jnp.float32) \
            if bias else None
        act_fn = {"relu": jax.nn.relu, "silu": jax.nn.silu,
                  "gelu": lambda a: jax.nn.gelu(a, approximate=False)}[act]

        def ref(x, w1, w2):
            u = x @ w1 + (b1 if bias else 0.0)
            return act_fn(u) @ w2 + (b2 if bias else 0.0)

        y = FB.fused_ffn(x, w1, w2, b1, b2, activation=act)
        yr = ref(x, w1, w2)
        scale = max(float(jnp.abs(yr).max()), 1e-6)
        assert float(jnp.abs(y - yr).max()) / scale < 1e-5

        gf = jax.grad(lambda *a: jnp.sum(
            FB.fused_ffn(*a, b1, b2, activation=act) ** 2),
            argnums=(0, 1, 2))(x, w1, w2)
        gr = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                      argnums=(0, 1, 2))(x, w1, w2)
        for a, b in zip(gf, gr):
            scale = max(float(jnp.abs(b).max()), 1e-6)
            assert float(jnp.abs(a - b).max()) / scale < 2e-5

    def test_unsupported_activation_raises(self):
        x = jnp.zeros((8, 128), jnp.float32)
        w = jnp.zeros((128, 128), jnp.float32)
        with pytest.raises(ValueError, match="activation"):
            FB.fused_mlp(x, w, w, w, activation="tanh")


# ---------------------------------------------------------------------------
# cost model: the fused kernels' HBM bytes beat the unfused jaxpr
# ---------------------------------------------------------------------------

class TestCostModelBytes:
    """Acceptance: on llama block shapes, each fused kernel accesses
    strictly fewer (cost-model, unfused-equivalent) HBM bytes than the
    reference lowering — forward alone AND through the gradient."""

    def _cost(self, fn, *args):
        from paddle_tpu.analysis import check
        rep = check(fn, *args, passes=["cost-model"])
        return rep.extras["cost"]

    def test_qkv_fused_fewer_bytes(self):
        # llama-block proportions: d model, dq = d, GQA kv at d/2
        t, d, dq, dkv = 512, 128, 128, 128
        x = jnp.zeros((t, d), jnp.bfloat16)
        wn = jnp.ones((d,), jnp.bfloat16)
        wq = jnp.zeros((d, dq), jnp.bfloat16)
        wk = jnp.zeros((d, dkv), jnp.bfloat16)
        wv = jnp.zeros((d, dkv), jnp.bfloat16)

        def fused(x, wn, wq, wk, wv):
            return FB.fused_rmsnorm_qkv(x, wn, wq, wk, wv, epsilon=EPS)

        fwd_fused = self._cost(fused, x, wn, wq, wk, wv)
        fwd_ref = self._cost(_qkv_ref, x, wn, wq, wk, wv)
        assert fwd_fused.total_bytes < 0.7 * fwd_ref.total_bytes, \
            (fwd_fused.total_bytes, fwd_ref.total_bytes)

        def g(fn):
            return jax.grad(lambda *a: sum(
                jnp.sum(o.astype(jnp.float32) ** 2) for o in fn(*a)))

        grad_fused = self._cost(g(fused), x, wn, wq, wk, wv)
        grad_ref = self._cost(g(_qkv_ref), x, wn, wq, wk, wv)
        assert grad_fused.total_bytes < grad_ref.total_bytes, \
            (grad_fused.total_bytes, grad_ref.total_bytes)

    def test_mlp_fused_fewer_bytes(self):
        # f/d = 4 and t >> d: the llama bench regime where the [T, f]
        # hidden intermediate dominates the traffic
        t, d, f = 1024, 128, 512
        x = jnp.zeros((t, d), jnp.bfloat16)
        wg = jnp.zeros((d, f), jnp.bfloat16)
        wu = jnp.zeros((d, f), jnp.bfloat16)
        wd = jnp.zeros((f, d), jnp.bfloat16)

        fwd_fused = self._cost(FB.fused_mlp, x, wg, wu, wd)
        fwd_ref = self._cost(_mlp_ref, x, wg, wu, wd)
        assert fwd_fused.total_bytes < 0.7 * fwd_ref.total_bytes, \
            (fwd_fused.total_bytes, fwd_ref.total_bytes)

        def g(fn):
            return jax.grad(lambda *a: jnp.sum(
                fn(*a).astype(jnp.float32) ** 2))

        grad_fused = self._cost(g(FB.fused_mlp), x, wg, wu, wd)
        grad_ref = self._cost(g(_mlp_ref), x, wg, wu, wd)
        assert grad_fused.total_bytes < grad_ref.total_bytes, \
            (grad_fused.total_bytes, grad_ref.total_bytes)


# ---------------------------------------------------------------------------
# in-model routing (llama decoder block + nn.Transformer FFN)
# ---------------------------------------------------------------------------

def _eligible_cfg():
    from paddle_tpu.models import LlamaConfig
    return LlamaConfig.tiny(hidden_size=128, intermediate_size=256,
                            num_attention_heads=2, num_key_value_heads=2,
                            vocab_size=256)


class TestRouting:
    def _layer_jaxpr(self, monkeypatch, knob):
        import paddle_tpu as pp
        from paddle_tpu.core.functional import functional_call, params_of
        from paddle_tpu.models import LlamaForCausalLM
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", knob)
        pp.seed(0)
        model = LlamaForCausalLM(_eligible_cfg())
        layer = model.model.layers[0]
        p = params_of(layer)
        x = jnp.zeros((2, 16, 128), jnp.float32)
        cos = unwrap(model.model.rope_cos)
        sin = unwrap(model.model.rope_sin)

        def f(p, x):    # fresh closure: make_jaxpr caches by identity
            return unwrap(functional_call(layer, p, x, cos, sin))

        return str(jax.make_jaxpr(f)(p, x))

    def test_knob_routes_and_zero_restores_previous_path(self, monkeypatch):
        """Acceptance: PADDLE_TPU_FUSED_BLOCK=0 restores the exact
        previous (pre-megakernel) lowering — no Pallas call anywhere in
        the decoder block jaxpr; =1 fuses both segments."""
        j1 = self._layer_jaxpr(monkeypatch, "1")
        j0 = self._layer_jaxpr(monkeypatch, "0")
        assert j1.count("pallas_call") >= 2      # rmsnorm+QKV and MLP
        assert "pallas_call" not in j0
        assert "dot_general" in j0               # the unfused matmul chain

    def test_logits_parity_knob_on_off(self, monkeypatch):
        import paddle_tpu as pp
        from paddle_tpu.models import LlamaForCausalLM
        rng = np.random.default_rng(7)
        ids = rng.integers(0, 256, (2, 16)).astype(np.int32)
        pp.seed(0)
        model = LlamaForCausalLM(_eligible_cfg())
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        l1 = np.asarray(model(pp.to_tensor(ids)).numpy(), np.float32)
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "0")
        l0 = np.asarray(model(pp.to_tensor(ids)).numpy(), np.float32)
        assert np.abs(l1 - l0).max() < 2e-4, np.abs(l1 - l0).max()

    @pytest.mark.slow
    def test_trainstep_losses_match_reference_path(self, monkeypatch):
        import paddle_tpu as pp
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import LlamaForCausalLM
        rng = np.random.default_rng(8)
        ids = rng.integers(0, 256, (2, 17)).astype(np.int32)
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

        def run(knob):
            monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", knob)
            pp.seed(0)
            model = LlamaForCausalLM(_eligible_cfg())
            opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
            step = TrainStep(model, opt)
            return [float(step(batch)) for _ in range(3)]

        l1, l0 = run("1"), run("0")
        assert all(abs(a - b) < 5e-4 for a, b in zip(l1, l0)), (l1, l0)
        assert l1[-1] < l1[0]

    def test_ineligible_config_takes_reference_path(self, monkeypatch):
        """The stock tiny config (d=64) cannot tile the VPU lanes: the
        knob stays on but every block routes reference, counted."""
        import paddle_tpu as pp
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.observability import default_registry
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        pp.seed(0)
        model = LlamaForCausalLM(LlamaConfig.tiny())
        m = default_registry().counter(
            "paddle_tpu_fused_block_path_total",
            labelnames=("kernel", "path"))
        before = {"/".join(k): c.value() for k, c in m.series()}
        ids = np.zeros((2, 16), np.int32)
        jaxpr = str(jax.make_jaxpr(
            lambda a: unwrap(model(a)))(jnp.asarray(ids)))
        assert "pallas_call" not in jaxpr
        after = {"/".join(k): c.value() for k, c in m.series()}
        assert after.get("rmsnorm_qkv/reference", 0) > \
            before.get("rmsnorm_qkv/reference", 0)
        assert after.get("mlp/reference", 0) > before.get("mlp/reference", 0)

    def test_decode_path_with_knob_on(self, monkeypatch):
        """Single-token decode rows (batch < 8) fall back cleanly —
        generation works with the knob forced on."""
        import paddle_tpu as pp
        from paddle_tpu.models import LlamaForCausalLM
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        pp.seed(0)
        model = LlamaForCausalLM(_eligible_cfg())
        ids = np.random.default_rng(9).integers(0, 256, (2, 8)) \
            .astype(np.int32)
        out = model.generate(pp.to_tensor(ids), max_new_tokens=3)
        arr = out[0] if isinstance(out, (tuple, list)) else out
        assert np.asarray(arr.numpy() if hasattr(arr, "numpy")
                          else arr).shape[1] == 11

    def test_encoder_ffn_routes_and_matches(self, monkeypatch):
        import paddle_tpu as pp
        import paddle_tpu.nn as nn
        rng = np.random.default_rng(10)
        src = pp.to_tensor(rng.standard_normal((2, 8, 128))
                           .astype(np.float32))
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        enc = nn.TransformerEncoderLayer(128, 2, 256, dropout=0.0,
                                         activation="gelu")
        enc.eval()
        y1 = enc(src).numpy()
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "0")
        y0 = enc(src).numpy()
        assert np.abs(np.asarray(y1, np.float32)
                      - np.asarray(y0, np.float32)).max() < 2e-5

    def test_encoder_ffn_dropout_training_falls_back(self, monkeypatch):
        import paddle_tpu as pp
        import paddle_tpu.nn as nn
        from paddle_tpu.observability import default_registry
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        enc = nn.TransformerEncoderLayer(128, 2, 256, dropout=0.1,
                                         activation="relu")
        enc.train()
        m = default_registry().counter(
            "paddle_tpu_fused_block_path_total",
            labelnames=("kernel", "path"))
        before = {"/".join(k): c.value() for k, c in m.series()}
        src = pp.to_tensor(np.zeros((2, 8, 128), np.float32))
        enc(src)
        after = {"/".join(k): c.value() for k, c in m.series()}
        assert after.get("ffn/reference", 0) > before.get("ffn/reference", 0)
        assert after.get("ffn/fused", 0) == before.get("ffn/fused", 0)


# ---------------------------------------------------------------------------
# autoshard checker round-trip on the 8-device harness (acceptance)
# ---------------------------------------------------------------------------

class TestAutoshardRoundTrip:
    def test_fused_model_roundtrips_checker_clean(self, monkeypatch):
        import paddle_tpu as pp
        from paddle_tpu.analysis import autoshard
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import LlamaForCausalLM
        if len(jax.devices()) < 8:
            pytest.skip("needs the virtual 8-device CPU mesh")
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "1")
        pp.seed(0)
        model = LlamaForCausalLM(_eligible_cfg())
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt)
        batch = {"input_ids": jax.ShapeDtypeStruct((8, 16), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((8, 16), jnp.int32)}
        res = autoshard.plan(step, batch, n_devices=8, topk=2)
        assert res.plans
        for p in res.plans:
            rep = p.verify(step, batch)
            assert not rep.errors() and not rep.warnings(), (
                p.candidate.label + "\n" + rep.format())


# ---------------------------------------------------------------------------
# autotune cache v2
# ---------------------------------------------------------------------------

@pytest.fixture()
def tuned(tmp_path, monkeypatch):
    """Isolated cache file + disabled seed layer, restored afterwards."""
    path = tmp_path / "autotune.json"
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(path))
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_SEED", "0")
    at.reload()
    yield path
    at.reload()


class TestAutotuneCache:
    def test_miss_measures_persists_then_hits(self, tuned):
        calls = []

        def bench(c):
            calls.append(c)
            return {(64, 128): 0.5, (128, 128): 0.1}[c]

        got = at.autotune("fused_qkv", "k1@cpu-interpret",
                          [(64, 128), (128, 128)], bench, (8, 128))
        assert got == (128, 128) and len(calls) == 2
        # fresh process simulation: reload from disk, bench must not run
        at.reload()
        got2 = at.autotune("fused_qkv", "k1@cpu-interpret",
                           [(64, 128), (128, 128)],
                           lambda c: pytest.fail("re-timed"), (8, 128))
        assert tuple(got2) == (128, 128)
        raw = json.loads(tuned.read_text())
        assert raw["version"] == at.CACHE_VERSION
        assert raw["entries"]["fused_qkv|k1@cpu-interpret"] == [128, 128]

    def test_version_mismatch_silently_invalidated(self, tuned):
        # v1-era flat schema: must be ignored, not raised on
        tuned.write_text(json.dumps({"fused_qkv|old": [999, 999]}))
        at.reload()
        assert at.cached_entries() == {}
        got = at.autotune("fused_qkv", "old", [(64, 128)],
                          lambda c: 0.1, (8, 128))
        assert got == (64, 128)                  # measured, not the stale 999

    def test_corrupt_cache_tolerated(self, tuned):
        tuned.write_text('{"version": 2, "entries": {"fused_')  # truncated
        at.reload()
        assert at.cached_entries() == {}
        # and the next save round-trips cleanly over the corpse
        at.autotune("fused_mlp", "k@cpu-interpret", [(64, 128)],
                    lambda c: 0.1, (8, 128))
        at.reload()
        assert at.cached_entries() == {"fused_mlp|k@cpu-interpret": [64, 128]}

    def test_backend_component_separates_namespaces(self, tuned):
        key_cpu = at.qkv_key(512, 128, 128, 128, 128, "float32",
                             interpret=True)
        key_tpu = at.qkv_key(512, 128, 128, 128, 128, "float32",
                             backend="tpu:TPU_v5_lite")
        assert key_cpu != key_tpu
        assert key_cpu.endswith("@cpu-interpret")
        at.autotune("fused_qkv", key_cpu, [(64, 128)], lambda c: 0.1,
                    (8, 128))
        benched = []
        at.autotune("fused_qkv", key_tpu, [(256, 256)],
                    lambda c: benched.append(c) or 0.1, (8, 128))
        assert benched, "TPU key was served from the CPU entry"

    def test_dtype_in_keys(self, tuned):
        a = at.mlp_key(512, 128, 512, "bfloat16", interpret=True)
        b = at.mlp_key(512, 128, 512, "float32", interpret=True)
        assert a != b

    def test_hit_miss_counters(self, tuned):
        from paddle_tpu.observability import default_registry
        m = default_registry().counter(
            "paddle_tpu_autotune_cache_total", labelnames=("op", "result"))
        before = {"/".join(k): c.value() for k, c in m.series()}
        at.autotune("fused_mlp", "c@cpu-interpret", [(64, 128)],
                    lambda c: 0.1, (8, 128))
        at.autotune("fused_mlp", "c@cpu-interpret", [(64, 128)],
                    lambda c: 0.1, (8, 128))
        after = {"/".join(k): c.value() for k, c in m.series()}
        assert after.get("fused_mlp/miss", 0) == \
            before.get("fused_mlp/miss", 0) + 1
        assert after.get("fused_mlp/hit", 0) == \
            before.get("fused_mlp/hit", 0) + 1

    def test_seed_layer_loads_and_user_overrides(self, tmp_path,
                                                 monkeypatch):
        seed = tmp_path / "seed.json"
        user = tmp_path / "user.json"
        seed.write_text(json.dumps({
            "version": at.CACHE_VERSION,
            "entries": {"fused_mlp|s@tpu:v5": [128, 256],
                        "flash|f@tpu:v5": [256, 256, True]}}))
        user.write_text(json.dumps({
            "version": at.CACHE_VERSION,
            "entries": {"fused_mlp|s@tpu:v5": [256, 512]}}))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_SEED", str(seed))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(user))
        at.reload()
        entries = at.cached_entries()
        assert entries["flash|f@tpu:v5"] == [256, 256, True]   # from seed
        assert entries["fused_mlp|s@tpu:v5"] == [256, 512]     # user wins
        at.reload()

    def test_sweep_dry_run_cli_roundtrip(self, tuned):
        rc = at.main(["--sweep", "--dry-run", "--cache", str(tuned)])
        assert rc == 0
        at.reload()
        entries = at.cached_entries()
        ops = {k.split("|", 1)[0] for k in entries}
        assert {"flash", "fused_ce", "fused_qkv", "fused_mlp"} <= ops
        # every entry hits without benching (fresh-process semantics)
        for key, val in entries.items():
            op, k = key.split("|", 1)
            got = at.autotune(op, k, [tuple(val)],
                              lambda c: pytest.fail("re-timed"), None)
            assert tuple(got) == tuple(val)

    def test_sweep_target_tag(self, tuned):
        rc = at.main(["--sweep", "--dry-run", "--cache", str(tuned),
                      "--target", "tpu:TPU_v5_lite", "--ops", "fused_mlp"])
        assert rc == 0
        at.reload()
        assert all(k.endswith("@tpu:TPU_v5_lite")
                   for k in at.cached_entries())

    def test_default_blocks_divide_shapes(self):
        from paddle_tpu.ops.pallas.fused_block import (_default_mlp_blocks,
                                                       _default_qkv_blocks)
        for t, d, dq, dkv in [(8192, 2048, 2048, 1024),
                              (8192, 4096, 4096, 1024), (64, 128, 128, 128)]:
            bt, bo = _default_qkv_blocks(t, d, dq, dkv, dkv, "bfloat16")
            assert t % bt == 0 and dq % bo == 0 and dkv % bo == 0
        for t, d, f in [(8192, 2048, 7168), (8192, 4096, 14336),
                        (64, 128, 512)]:
            bt, bf = _default_mlp_blocks(t, d, f, "bfloat16")
            assert t % bt == 0 and f % bf == 0
