"""Model-family tests: GPT, MoE-LLM (DeepSeek/Qwen2-MoE shape), DiT,
ResNet — forward shapes, training steps, sharded compile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pp
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (DiT, DiTConfig, GPTConfig, GPTForCausalLM,
                               MoEConfig, MoEForCausalLM)


class TestGPT:
    def test_forward_and_loss(self):
        pp.seed(0)
        cfg = GPTConfig.tiny()
        model = GPTForCausalLM(cfg)
        ids = pp.to_tensor(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 16)).astype("int32"))
        logits = model(ids)
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        # tied embeddings: no separate lm_head parameter
        assert model.lm_head is None
        loss = model.loss(ids, ids)
        assert np.isfinite(float(loss.numpy()))

    def test_train_step_reduces_loss(self):
        pp.seed(0)
        cfg = GPTConfig.tiny(vocab_size=128)
        model = GPTForCausalLM(cfg)
        opt = pp.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (4, 17))
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
        losses = [float(step(batch)) for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_sharded_compile(self):
        pp.seed(0)
        cfg = GPTConfig.tiny(vocab_size=128, hidden_size=64)
        model = GPTForCausalLM(cfg)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
        rules = GPTForCausalLM.partition_specs(cfg)
        specs = {n: GPTForCausalLM.spec_for(n, rules)
                 for n in model.state_dict(keep_vars=True)}
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, mesh=mesh, param_specs=specs,
                         batch_spec=P("dp"))
        ids = np.random.default_rng(0).integers(0, 128, (4, 17))
        loss = step({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
        assert np.isfinite(float(loss))


class TestMoELLM:
    def test_forward_and_aux_loss(self):
        pp.seed(0)
        cfg = MoEConfig.tiny()
        model = MoEForCausalLM(cfg)
        ids = pp.to_tensor(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (2, 16)).astype("int32"))
        logits = model(ids)
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        aux = model.model.aux_loss()
        assert aux is not None and np.isfinite(float(np.asarray(aux)))
        # layer 0 dense (first_k_dense_replace=1), layer 1 MoE
        assert model.model.layers[0].is_dense
        assert not model.model.layers[1].is_dense

    @pytest.mark.slow
    def test_train_step_with_ep_sharding(self):
        pp.seed(0)
        cfg = MoEConfig.tiny(num_experts=4)
        model = MoEForCausalLM(cfg)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "ep"))
        rules = MoEForCausalLM.partition_specs(cfg)
        specs = {n: MoEForCausalLM.spec_for(n, rules)
                 for n in model.state_dict(keep_vars=True)}
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

        def loss_fn(out, y):  # routed through model.loss for the aux term
            raise AssertionError("unused")

        step = TrainStep(model, opt, mesh=mesh, param_specs=specs,
                         batch_spec=P("dp"),
                         loss_fn=None)
        ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 17))
        losses = [float(step({"input_ids": ids[:, :-1],
                              "labels": ids[:, 1:]})) for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    @pytest.mark.slow
    def test_expert_grads_flow(self):
        pp.seed(0)
        cfg = MoEConfig.tiny(num_experts=4, first_k_dense_replace=0)
        model = MoEForCausalLM(cfg)
        from paddle_tpu.core.functional import functional_call, params_of
        params = params_of(model)

        def loss(ps, ids):
            out = functional_call(model, ps, pp.Tensor(ids))
            out = out._data if hasattr(out, "_data") else out
            return (out.astype(jnp.float32) ** 2).mean()

        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (1, 8)), jnp.int32)
        g = jax.grad(loss)(params, ids)
        w1_key = [k for k in g if "experts.w1" in k][0]
        assert float(jnp.abs(g[w1_key]).sum()) > 0


class TestDiT:
    def test_forward_shapes(self):
        pp.seed(0)
        cfg = DiTConfig.tiny()
        model = DiT(cfg)
        x = pp.randn([2, cfg.in_channels, cfg.input_size, cfg.input_size])
        t = pp.to_tensor(np.array([3, 7], np.int32))
        y = pp.to_tensor(np.array([1, 2], np.int32))
        out = model(x, t, y)
        out_ch = cfg.in_channels * 2  # learn_sigma
        assert tuple(out.shape) == (2, out_ch, cfg.input_size,
                                    cfg.input_size)

    def test_adaln_zero_init_is_identity_path(self):
        """final layer zero-init → output starts at exactly zero."""
        pp.seed(0)
        cfg = DiTConfig.tiny()
        model = DiT(cfg)
        x = pp.randn([1, cfg.in_channels, cfg.input_size, cfg.input_size])
        t = pp.to_tensor(np.array([0], np.int32))
        y = pp.to_tensor(np.array([0], np.int32))
        out = model(x, t, y)
        np.testing.assert_allclose(out.numpy(), 0.0)

    def test_training_reduces_mse(self):
        pp.seed(0)
        cfg = DiTConfig.tiny()
        model = DiT(cfg)
        from paddle_tpu.core.functional import functional_call, params_of
        from paddle_tpu.core.dispatch import unwrap
        params = params_of(model)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 4, 8, 8)), jnp.float32)
        noise = jnp.asarray(rng.normal(size=(2, 4, 8, 8)), jnp.float32)
        t = jnp.asarray([1, 2], jnp.int32)
        y = jnp.asarray([0, 1], jnp.int32)

        def loss(ps):
            out = functional_call(model, ps, pp.Tensor(x), pp.Tensor(t),
                                  pp.Tensor(y))
            eps = unwrap(out)[:, :4]
            return jnp.mean((eps - noise) ** 2)

        @jax.jit
        def step(ps):
            l, g = jax.value_and_grad(loss)(ps)
            return l, jax.tree.map(lambda p, gr: p - 1e-2 * gr, ps, g)

        l0, params = step(params)
        for _ in range(10):
            l, params = step(params)
        assert float(l) < float(l0)

    def test_patchify_roundtrip(self):
        cfg = DiTConfig.tiny()
        model = DiT(cfg)
        x = np.arange(2 * 4 * 8 * 8, dtype=np.float32).reshape(2, 4, 8, 8)
        tokens = model.patchify(pp.to_tensor(x))
        assert tokens.shape == (2, cfg.num_patches,
                                cfg.patch_size ** 2 * 4)
        back = model.unpatchify(tokens, 4)
        np.testing.assert_allclose(np.asarray(back), x)


class TestResNet:
    @pytest.mark.slow
    def test_resnet18_forward(self):
        from paddle_tpu.vision.models import resnet18
        pp.seed(0)
        net = resnet18(num_classes=10)
        x = pp.randn([2, 3, 32, 32])
        out = net(x)
        assert tuple(out.shape) == (2, 10)

    @pytest.mark.slow
    def test_resnet50_bottleneck(self):
        from paddle_tpu.vision.models import resnet50
        pp.seed(0)
        net = resnet50(num_classes=4)
        x = pp.randn([1, 3, 64, 64])
        assert tuple(net(x).shape) == (1, 4)

    @pytest.mark.slow
    def test_train_step(self):
        from paddle_tpu.vision.models import resnet18
        pp.seed(0)
        net = resnet18(num_classes=4)
        opt = pp.optimizer.Momentum(learning_rate=1e-2,
                                    parameters=net.parameters())

        def loss_fn(out, y):
            return pp.nn.functional.cross_entropy(out, y)

        step = TrainStep(net, opt, loss_fn=loss_fn)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 3, 32, 32)).astype("float32")
        y = (np.arange(8) % 4).astype("int64")
        losses = [float(step((x, y))) for _ in range(5)]
        assert np.isfinite(losses).all() if hasattr(
            np.isfinite(losses), "all") else all(
            np.isfinite(l) for l in losses)

    def test_transforms(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.default_rng(0).random((40, 48, 3)) * 255
               ).astype(np.uint8)
        pipeline = T.Compose([
            T.Resize(32), T.CenterCrop(28), T.RandomHorizontalFlip(1.0),
            T.ToTensor(),
            T.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5]),
        ])
        out = pipeline(img)
        assert out.shape == (3, 28, 28)
        assert out.dtype == np.float32
        assert -1.01 <= out.min() and out.max() <= 1.01


class TestErnie:
    """ERNIE family (VERDICT r3 Missing #1/#8): encoder NLU models +
    ERNIE 4.5 MoE decoder (models/ernie.py)."""

    def test_encoder_forward_shapes(self):
        from paddle_tpu.models import ErnieConfig, ErnieModel
        pp.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieModel(cfg)
        ids = pp.to_tensor(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 12)).astype("int32"))
        h, pooled = model(ids)
        assert tuple(h.shape) == (2, 12, cfg.hidden_size)
        assert tuple(pooled.shape) == (2, cfg.hidden_size)

    @pytest.mark.slow
    def test_classifier_trains_to_loss_drop(self):
        from paddle_tpu.models import (ErnieConfig,
                                       ErnieForSequenceClassification)
        pp.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForSequenceClassification(cfg, num_classes=2)
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (8, 12)).astype("int32")
        # learnable signal: class = whether token 0 appears
        labels = (ids == 0).any(axis=1).astype("int64")
        ids_t, y_t = pp.to_tensor(ids), pp.to_tensor(labels)
        losses = []
        for _ in range(12):
            loss = model.loss(ids_t, y_t)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_masked_lm_ignore_index(self):
        from paddle_tpu.models import ErnieConfig, ErnieForMaskedLM
        pp.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForMaskedLM(cfg)
        rng = np.random.default_rng(0)
        ids = pp.to_tensor(rng.integers(0, cfg.vocab_size,
                                        (2, 10)).astype("int32"))
        logits = model(ids)
        assert tuple(logits.shape) == (2, 10, cfg.vocab_size)
        labels = np.full((2, 10), -100, np.int64)
        labels[:, 3] = 7          # only one masked position scored
        loss = model.loss(ids, pp.to_tensor(labels))
        assert np.isfinite(float(loss))

    def test_ernie45_decoder_train_step(self):
        from paddle_tpu.models import ErnieForCausalLM, ernie45_moe_config
        pp.seed(0)
        cfg = ernie45_moe_config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            moe_intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, num_experts=4,
            num_experts_per_tok=2, num_shared_experts=1,
            max_position_embeddings=64, dtype="float32")
        model = ErnieForCausalLM(cfg)
        # heterogeneous MoE: first layer dense, second routed+shared
        assert model.model.layers[0].is_dense
        assert not model.model.layers[1].is_dense
        opt = pp.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (4, 17))
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
        losses = [float(step(batch)) for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_ernie45_sharding_rules(self):
        from paddle_tpu.models import ErnieForCausalLM, ernie45_moe_config
        cfg = ernie45_moe_config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            moe_intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, num_experts=8,
            num_experts_per_tok=2, num_shared_experts=1,
            max_position_embeddings=64, dtype="float32")
        rules = ErnieForCausalLM.partition_specs(cfg)
        assert ErnieForCausalLM.spec_for(
            "model.layers_1.moe.experts.w1", rules) == P("ep", None, "tp")


class TestConvFamilyTraining:
    """Conv-family models train to a loss drop (the vision-zoo models the
    conv_train_bench measures; VERDICT r4 Next #3)."""

    @pytest.mark.slow
    def test_resnet18_reduces_loss(self):
        from paddle_tpu.vision.models import resnet18
        pp.seed(0)
        net = resnet18(num_classes=4)
        opt = pp.optimizer.Momentum(learning_rate=5e-3,
                                    parameters=net.parameters())

        def loss_fn(out, y):
            return pp.nn.functional.cross_entropy(out, y)

        step = TrainStep(net, opt, loss_fn=loss_fn)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 3, 32, 32)).astype("float32")
        y = (np.arange(8) % 4).astype("int64")
        losses = [float(step((x, y))) for _ in range(8)]
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow
    def test_crnn_ctc_reduces_loss(self):
        """conv backbone -> BiLSTM -> CTC (the PP-OCR recognizer shape)
        trains: loss drops over a few steps on a fixed batch."""
        import jax
        import jax.numpy as jnp
        import functools
        from paddle_tpu.core.dispatch import unwrap
        from paddle_tpu.core.functional import functional_call, params_of
        from paddle_tpu.nn import functional as F
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.layer import Layer

        class CRNN(Layer):
            def __init__(self):
                super().__init__()
                self.net = nn.Sequential(
                    nn.Conv2D(3, 16, 3, stride=2, padding=1), nn.ReLU(),
                    nn.Conv2D(16, 32, 3, stride=(2, 1), padding=1),
                    nn.ReLU(),
                    nn.Conv2D(32, 32, (8, 1), stride=1, padding=0),
                    nn.ReLU(),
                )
                self.rnn = nn.LSTM(32, 24, direction="bidirectional")
                self.head = nn.Linear(48, 11)

            def forward(self, x):
                feat = unwrap(self.net(x))                # [b, C, 1, W']
                seq = feat[:, :, 0, :].transpose(0, 2, 1)
                out, _ = self.rnn(pp.Tensor(seq))
                logits = unwrap(self.head(out))
                return jax.nn.log_softmax(
                    logits.astype(jnp.float32), -1).transpose(1, 0, 2)

        pp.seed(1)
        model = CRNN()
        params = params_of(model)
        rng = np.random.default_rng(0)
        b, L = 4, 5
        x = jnp.asarray(rng.normal(size=(b, 3, 32, 32)), jnp.float32)
        labels = jnp.asarray(rng.integers(1, 10, (b, L)), jnp.int32)

        def loss_of(ps):
            logp = unwrap(functional_call(model, ps, pp.Tensor(x)))
            T = logp.shape[0]
            return unwrap(F.ctc_loss(
                logp, labels, jnp.full((b,), T, jnp.int32),
                jnp.full((b,), L, jnp.int32), blank=0, reduction="mean"))

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(ps):
            l, g = jax.value_and_grad(loss_of)(ps)
            return l, jax.tree.map(lambda p, gr: p - 0.01 * gr, ps, g)

        losses = []
        for _ in range(8):
            l, params = step(params)
            losses.append(float(l))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
