"""Ring attention / Ulysses / Pallas flash attention tests.

Parity oracle: the dense XLA attention on the full (unsharded) sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.nn.functional.attention import _sdpa_reference


def make_qkv(b=2, s=64, h=4, d=16, kv_heads=None, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    return q, k, v


def sp_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        mesh = sp_mesh()
        fn = dist.make_ring_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_broadcast(self):
        q, k, v = make_qkv(h=8, kv_heads=2)
        mesh = sp_mesh()
        got = jax.jit(dist.make_ring_attention(mesh, causal=True))(q, k, v)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow  # ring bwd trace; CI SPMD + MoE gates run it
    def test_grads_match_dense(self):
        q, k, v = make_qkv(s=32)
        mesh = sp_mesh(4)
        ring = dist.make_ring_attention(mesh, causal=True)

        g1 = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (
            _sdpa_reference(q, k, v, is_causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv(h=8)
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_heads_not_divisible_raises(self):
        q, k, v = make_qkv(h=4)  # 4 heads, sp=8
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh)
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(fn)(q, k, v)


class TestPallasFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=256, d=64)
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, h=8, kv_heads=2, d=64)
        got = flash_attention(q, k, v, causal=True, interpret=True)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_backward_blockwise(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, d=64)
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (_sdpa_reference(
            q, k, v, is_causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_indivisible_seq_raises(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=100, d=64)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)

    @pytest.mark.slow
    def test_backward_pallas_gqa_matches_dense(self):
        # grouped-GQA through the Pallas dkv kernel (query-group inner axis)
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=256, h=8, kv_heads=2, d=64)
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True, block_q=64,
            block_k=128) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (_sdpa_reference(
            q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
            is_causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):  # repeat is inside the oracle lambda, so
            # autodiff already sums kv grads over the query group
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_pallas_bwd_equals_blockwise_bwd(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, h=4, kv_heads=2, d=64)

        def loss(pb):
            return lambda q, k, v: (flash_attention(
                q, k, v, causal=True, interpret=True,
                pallas_bwd=pb) ** 2).sum()

        gp = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


class TestFusedRMSNorm:
    def _ref(self, x, w, res, eps=1e-5):
        h = x.astype(jnp.float32)
        if res is not None:
            h = h + res.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(h * h, -1, keepdims=True) + eps)
        return (h * inv * w).astype(x.dtype), h.astype(x.dtype)

    @pytest.mark.parametrize("with_res", [False, True])
    def test_forward_matches(self, with_res):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((4, 16, 128)),
                          jnp.float32) if with_res else None
        y, h = fused_rmsnorm(x, w, residual=res, interpret=True)
        wy, wh = self._ref(x, w, res)
        np.testing.assert_allclose(np.asarray(y), np.asarray(wy),
                                   rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(np.asarray(h), np.asarray(wh),
                                   rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("with_res", [False, True])
    def test_grads_match(self, with_res):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((2, 8, 128)),
                          jnp.float32) if with_res else None

        def lf(fused):
            def f(x, w, *r):
                rr = r[0] if with_res else None
                if fused:
                    y, h = fused_rmsnorm(x, w, residual=rr, interpret=True)
                else:
                    y, h = self._ref(x, w, rr)
                return jnp.sum(y ** 2) + jnp.sum(jnp.tanh(h))
            return f

        args = (x, w, res) if with_res else (x, w)
        an = (0, 1, 2) if with_res else (0, 1)
        gf = jax.grad(lf(True), argnums=an)(*args)
        gr = jax.grad(lf(False), argnums=an)(*args)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_fallback_on_untileable_shapes(self):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        x = jnp.ones((3, 5, 100), jnp.float32)   # d % 128 != 0
        w = jnp.ones((100,), jnp.float32)
        y, h = fused_rmsnorm(x, w)
        wy, wh = self._ref(x, w, None)
        np.testing.assert_allclose(np.asarray(y), np.asarray(wy),
                                   rtol=1e-6)


class TestAutotuneCache:
    def test_measures_once_and_persists(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        at.clear_cache()
        calls = []

        def bench(c):
            calls.append(c)
            return {16: 2.0, 32: 1.0, 64: 3.0}[c[0]]

        got = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got) == (32,)
        assert len(calls) == 3
        # second call: cached, no measurement
        got2 = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got2) == (32,) and len(calls) == 3
        # new process simulation: reload from disk
        at._mem_cache.clear()
        at._loaded = False
        got3 = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got3) == (32,) and len(calls) == 3

    def test_disabled_uses_default(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "c.json"))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        at.clear_cache()
        got = at.autotune("op", "k2", [(1,), (2,)],
                          lambda c: 1 / 0, (9,))
        assert got == (9,)

    def test_flash_candidates_respect_vmem(self):
        from paddle_tpu.ops.pallas.autotune import _flash_candidates
        cands = _flash_candidates(8192, 128, "bfloat16")
        assert (128, 128, True) in cands and (128, 128, False) in cands
        assert all(bq * bk * 4 < 10 * (1 << 20) for bq, bk, _ in cands)


# ---------------------------------------------------------------------------
# flash-backed ring attention (ISSUE 18 tentpole, layer 2)
# ---------------------------------------------------------------------------


def _stripe(x, sp):
    """Natural order -> striped shards in rank order: global token
    j*sp + r lands at shard r, local slot j."""
    return jnp.concatenate([x[:, r::sp] for r in range(sp)], axis=1)


def _unstripe(y, sp):
    b, s = y.shape[:2]
    return jnp.swapaxes(y.reshape((b, sp, s // sp) + y.shape[2:]), 1, 2) \
        .reshape(y.shape)


class TestRingFlash:
    """``impl="flash"`` / PADDLE_TPU_RING_FLASH=1: per-hop flash kernel +
    lse merge.  Oracle: dense attention on the full sequence."""

    @pytest.mark.parametrize("sp", [2, 4])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_fp32(self, sp, causal):
        q, k, v = make_qkv(s=128)
        mesh = sp_mesh(sp)
        fn = dist.make_ring_attention(mesh, causal=causal, impl="flash")
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_dense_bf16(self, sp):
        q, k, v = make_qkv(s=128)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        mesh = sp_mesh(sp)
        fn = dist.make_ring_attention(mesh, causal=True, impl="flash")
        got = np.asarray(jax.jit(fn)(q, k, v), np.float32)
        want = np.asarray(_sdpa_reference(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), is_causal=True), np.float32)
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)

    def test_gqa(self):
        q, k, v = make_qkv(s=128, h=8, kv_heads=2)
        mesh = sp_mesh(4)
        fn = dist.make_ring_attention(mesh, causal=True, impl="flash")
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def _ring_jaxpr(self, monkeypatch, knob):
        monkeypatch.setenv("PADDLE_TPU_RING_FLASH", knob)
        mesh = sp_mesh(4)
        fn = dist.make_ring_attention(mesh, causal=True)

        def f(q, k, v):    # fresh closure: make_jaxpr caches by identity
            return fn(q, k, v)

        q, k, v = make_qkv(s=32)
        return str(jax.make_jaxpr(f)(q, k, v))

    def test_knob_routes_and_zero_restores_dense_path(self, monkeypatch):
        """Acceptance: knob off keeps the exact dense-fold program (no
        pallas_call, byte-identical before/after a knob-on trace); =1
        swaps the per-hop fold to the flash kernel."""
        j_base = self._ring_jaxpr(monkeypatch, "0")
        j_on = self._ring_jaxpr(monkeypatch, "1")
        j_off = self._ring_jaxpr(monkeypatch, "0")
        assert "pallas_call" not in j_base
        assert "pallas_call" in j_on
        assert j_base == j_off

    def test_overlap_knob_composes(self, monkeypatch):
        """PR 15's ppermute-before-fold overlap stays correct under the
        flash fold."""
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_OVERLAP", "1")
        q, k, v = make_qkv(s=128)
        mesh = sp_mesh(4)
        fn = dist.make_ring_attention(mesh, causal=True, impl="flash")
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow  # full bwd trace through the scan of switches
    def test_grads_match_dense(self):
        q, k, v = make_qkv(s=64)
        mesh = sp_mesh(4)
        ring = dist.make_ring_attention(mesh, causal=True, impl="flash")
        g1 = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (
            _sdpa_reference(q, k, v, is_causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-5)

    @pytest.mark.slow  # seq >> 2048: the long-context acceptance run
    def test_long_context_seq_4096(self):
        q, k, v = make_qkv(b=1, s=4096, h=2, d=64)
        mesh = sp_mesh(8)
        fn = dist.make_ring_attention(mesh, causal=True, impl="flash")
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


class TestStripedRing:
    """Striped layout (local slot j == global j*sp + rank): causal load
    balance.  Inputs/outputs travel striped; the oracle stripes the
    dense result."""

    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_dense_fp32(self, sp):
        q, k, v = make_qkv(s=64)
        mesh = sp_mesh(sp)
        fn = dist.make_striped_ring_attention(mesh)
        got = jax.jit(fn)(_stripe(q, sp), _stripe(k, sp), _stripe(v, sp))
        want = _stripe(_sdpa_reference(q, k, v, is_causal=True), sp)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_unstripe_roundtrip(self):
        x = jnp.arange(2 * 16 * 4 * 8, dtype=jnp.float32) \
            .reshape(2, 16, 4, 8)
        assert np.array_equal(np.asarray(_unstripe(_stripe(x, 4), 4)),
                              np.asarray(x))

    def test_bf16_causal_finite_and_matches(self):
        """Regression (ISSUE 18 satellite): striped hops with src > rank
        fully mask their first rows — before the finfo mask + alive
        guard, bf16 causal folded exp(mask - mask) == 1 garbage into
        those rows (NaN/garbage outputs)."""
        sp = 4
        q, k, v = make_qkv(s=64, seed=9)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        mesh = sp_mesh(sp)
        fn = dist.make_striped_ring_attention(mesh)
        got = np.asarray(jax.jit(fn)(
            _stripe(qb, sp), _stripe(kb, sp), _stripe(vb, sp)), np.float32)
        assert np.isfinite(got).all()
        want = np.asarray(_stripe(
            _sdpa_reference(q, k, v, is_causal=True), sp), np.float32)
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)


class TestMaskValue:
    def test_finite_and_summable_per_dtype(self):
        from paddle_tpu.distributed.sequence_parallel import mask_value
        for dt in (jnp.float32, jnp.bfloat16, jnp.float16):
            m = mask_value(dt)
            assert np.isfinite(m)
            # two masked scores (or mask + any finite score) must not
            # overflow the dtype — the -1e30 literal broke this for fp16
            assert np.isfinite(np.asarray(m + m, jnp.dtype(dt)))

    def test_padded_tail_rows_stay_finite(self):
        """A causal ring over a padded tail (queries whose keys are all
        masked in some hop) must produce finite outputs — the alive
        guard zeroes dead rows instead of folding exp(0)."""
        from paddle_tpu.distributed import shard_map
        from paddle_tpu.distributed.sequence_parallel import (
            striped_ring_attention)
        from jax.sharding import PartitionSpec as P
        sp = 4
        q, k, v = make_qkv(s=32, seed=11)
        qb, kb, vb = (_stripe(x, sp).astype(jnp.bfloat16)
                      for x in (q, k, v))
        mesh = sp_mesh(sp)
        spec = P(None, "sp", None, None)
        fn = shard_map(striped_ring_attention, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec,
                       legacy_check_rep=False)
        out = np.asarray(fn(qb, kb, vb), np.float32)
        assert np.isfinite(out).all()
