"""Ring attention / Ulysses / Pallas flash attention tests.

Parity oracle: the dense XLA attention on the full (unsharded) sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.nn.functional.attention import _sdpa_reference


def make_qkv(b=2, s=64, h=4, d=16, kv_heads=None, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    return q, k, v


def sp_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        mesh = sp_mesh()
        fn = dist.make_ring_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_broadcast(self):
        q, k, v = make_qkv(h=8, kv_heads=2)
        mesh = sp_mesh()
        got = jax.jit(dist.make_ring_attention(mesh, causal=True))(q, k, v)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_dense(self):
        q, k, v = make_qkv(s=32)
        mesh = sp_mesh(4)
        ring = dist.make_ring_attention(mesh, causal=True)

        g1 = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (
            _sdpa_reference(q, k, v, is_causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv(h=8)
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_heads_not_divisible_raises(self):
        q, k, v = make_qkv(h=4)  # 4 heads, sp=8
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh)
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(fn)(q, k, v)


class TestPallasFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=256, d=64)
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, h=8, kv_heads=2, d=64)
        got = flash_attention(q, k, v, causal=True, interpret=True)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow
    def test_backward_blockwise(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, d=64)
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (_sdpa_reference(
            q, k, v, is_causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_indivisible_seq_raises(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=100, d=64)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)

    @pytest.mark.slow
    def test_backward_pallas_gqa_matches_dense(self):
        # grouped-GQA through the Pallas dkv kernel (query-group inner axis)
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=256, h=8, kv_heads=2, d=64)
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True, block_q=64,
            block_k=128) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (_sdpa_reference(
            q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
            is_causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):  # repeat is inside the oracle lambda, so
            # autodiff already sums kv grads over the query group
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_pallas_bwd_equals_blockwise_bwd(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, h=4, kv_heads=2, d=64)

        def loss(pb):
            return lambda q, k, v: (flash_attention(
                q, k, v, causal=True, interpret=True,
                pallas_bwd=pb) ** 2).sum()

        gp = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gp, gb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


class TestFusedRMSNorm:
    def _ref(self, x, w, res, eps=1e-5):
        h = x.astype(jnp.float32)
        if res is not None:
            h = h + res.astype(jnp.float32)
        inv = jax.lax.rsqrt(jnp.mean(h * h, -1, keepdims=True) + eps)
        return (h * inv * w).astype(x.dtype), h.astype(x.dtype)

    @pytest.mark.parametrize("with_res", [False, True])
    def test_forward_matches(self, with_res):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((4, 16, 128)),
                          jnp.float32) if with_res else None
        y, h = fused_rmsnorm(x, w, residual=res, interpret=True)
        wy, wh = self._ref(x, w, res)
        np.testing.assert_allclose(np.asarray(y), np.asarray(wy),
                                   rtol=2e-6, atol=2e-6)
        np.testing.assert_allclose(np.asarray(h), np.asarray(wh),
                                   rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("with_res", [False, True])
    def test_grads_match(self, with_res):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
        res = jnp.asarray(rng.standard_normal((2, 8, 128)),
                          jnp.float32) if with_res else None

        def lf(fused):
            def f(x, w, *r):
                rr = r[0] if with_res else None
                if fused:
                    y, h = fused_rmsnorm(x, w, residual=rr, interpret=True)
                else:
                    y, h = self._ref(x, w, rr)
                return jnp.sum(y ** 2) + jnp.sum(jnp.tanh(h))
            return f

        args = (x, w, res) if with_res else (x, w)
        an = (0, 1, 2) if with_res else (0, 1)
        gf = jax.grad(lf(True), argnums=an)(*args)
        gr = jax.grad(lf(False), argnums=an)(*args)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_fallback_on_untileable_shapes(self):
        from paddle_tpu.ops.pallas.rmsnorm import fused_rmsnorm
        x = jnp.ones((3, 5, 100), jnp.float32)   # d % 128 != 0
        w = jnp.ones((100,), jnp.float32)
        y, h = fused_rmsnorm(x, w)
        wy, wh = self._ref(x, w, None)
        np.testing.assert_allclose(np.asarray(y), np.asarray(wy),
                                   rtol=1e-6)


class TestAutotuneCache:
    def test_measures_once_and_persists(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "cache.json"))
        at.clear_cache()
        calls = []

        def bench(c):
            calls.append(c)
            return {16: 2.0, 32: 1.0, 64: 3.0}[c[0]]

        got = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got) == (32,)
        assert len(calls) == 3
        # second call: cached, no measurement
        got2 = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got2) == (32,) and len(calls) == 3
        # new process simulation: reload from disk
        at._mem_cache.clear()
        at._loaded = False
        got3 = at.autotune("op", "k1", [(16,), (32,), (64,)], bench, (16,))
        assert tuple(got3) == (32,) and len(calls) == 3

    def test_disabled_uses_default(self, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "c.json"))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        at.clear_cache()
        got = at.autotune("op", "k2", [(1,), (2,)],
                          lambda c: 1 / 0, (9,))
        assert got == (9,)

    def test_flash_candidates_respect_vmem(self):
        from paddle_tpu.ops.pallas.autotune import _flash_candidates
        cands = _flash_candidates(8192, 128, "bfloat16")
        assert (128, 128, True) in cands and (128, 128, False) in cands
        assert all(bq * bk * 4 < 10 * (1 << 20) for bq, bk, _ in cands)
