"""Ring attention / Ulysses / Pallas flash attention tests.

Parity oracle: the dense XLA attention on the full (unsharded) sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.nn.functional.attention import _sdpa_reference


def make_qkv(b=2, s=64, h=4, d=16, kv_heads=None, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (b, s, kv_heads or h, d), jnp.float32) * 0.5
    return q, k, v


def sp_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv()
        mesh = sp_mesh()
        fn = dist.make_ring_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_broadcast(self):
        q, k, v = make_qkv(h=8, kv_heads=2)
        mesh = sp_mesh()
        got = jax.jit(dist.make_ring_attention(mesh, causal=True))(q, k, v)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_dense(self):
        q, k, v = make_qkv(s=32)
        mesh = sp_mesh(4)
        ring = dist.make_ring_attention(mesh, causal=True)

        g1 = jax.jit(jax.grad(lambda q, k, v: (ring(q, k, v) ** 2).sum(),
                              argnums=(0, 1, 2)))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (
            _sdpa_reference(q, k, v, is_causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=1e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        q, k, v = make_qkv(h=8)
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh, causal=causal)
        got = jax.jit(fn)(q, k, v)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_heads_not_divisible_raises(self):
        q, k, v = make_qkv(h=4)  # 4 heads, sp=8
        mesh = sp_mesh()
        fn = dist.make_ulysses_attention(mesh)
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(fn)(q, k, v)


class TestPallasFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=256, d=64)
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        want = _sdpa_reference(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, h=8, kv_heads=2, d=64)
        got = flash_attention(q, k, v, causal=True, interpret=True)
        want = _sdpa_reference(q, jnp.repeat(k, 4, 2), jnp.repeat(v, 4, 2),
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_backward_blockwise(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=128, d=64)
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (_sdpa_reference(
            q, k, v, is_causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_indivisible_seq_raises(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        q, k, v = make_qkv(s=100, d=64)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
