"""Schema-driven op tests: every ops.yaml entry runs through the OpTest
harness with its declared numpy oracle (reference: per-op OpTest files in
test/legacy_test generated from the same ops.yaml the kernels come from).

Also guards codegen drift: the checked-in generated_math.py must match what
the generator produces from the current ops.yaml.
"""

import numpy as np
import pytest
import scipy.special as sps

from paddle_tpu.ops.gen.generate import gen_module, load_entries
from paddle_tpu.ops import generated_math as gm
from paddle_tpu.testing import op_case, _rand

ENTRIES = load_entries()


def test_generated_file_in_sync():
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "paddle_tpu", "ops", "generated_math.py")
    with open(path) as f:
        on_disk = f.read()
    assert on_disk == gen_module(ENTRIES), (
        "generated_math.py is out of sync with ops.yaml — run "
        "python -m paddle_tpu.ops.gen.generate")


def test_schema_covers_300_ops():
    """VERDICT r3 item 7 'done' criterion: >= 300 generated ops."""
    assert len(ENTRIES) >= 300


def test_fft_module_surface():
    import paddle_tpu
    import numpy as np_
    out = paddle_tpu.fft.rfft(paddle_tpu.to_tensor(
        np_.ones(8, np_.float32)))
    assert out.shape == [5]


def _oracle_fn(entry):
    expr = entry.get("oracle")
    if expr is None:
        return None
    args = list(entry["args"])

    def fn(*vals, **attrs):
        ns = {"np": np, "sps": sps}
        ns.update(zip(args, vals))
        for a in entry.get("attrs") or []:
            if not a.get("required"):
                ns[a["name"]] = eval(a["default"], {"None": None})
        ns.update(attrs)
        return eval(expr, ns)  # noqa: S307 — in-repo schema strings
    return fn


def _expr_ns(seed=0):
    """Tiny input DSL for `kind: expr` entries: deterministic generators
    usable in the yaml's `inputs:` expressions."""
    rng = np.random.default_rng(seed)

    def rand(*shape, lo=-1.0, hi=1.0, dtype=np.float32):
        return (rng.uniform(lo, hi, shape)).astype(dtype)

    def randint(lo, hi, shape, dtype=np.int64):
        return rng.integers(lo, hi, shape).astype(dtype)

    def mask(*shape, p=0.5):
        return rng.uniform(0, 1, shape) < p

    def perm(n):
        return rng.permutation(n).astype(np.int64)

    def sorted_(*shape, lo=-1.0, hi=1.0):
        return np.sort(rng.uniform(lo, hi, shape).astype(np.float32), -1)

    def posdef(n):
        a = rng.standard_normal((n, n)).astype(np.float32)
        return a @ a.T + n * np.eye(n, dtype=np.float32)

    return {"np": np, "rand": rand, "randint": randint, "mask": mask,
            "perm": perm, "sorted": sorted_, "posdef": posdef}


def _cases(entry):
    t = entry.get("test") or {}
    kind = t.get("kind", "skip")
    if kind == "skip":
        return []
    op = getattr(gm, entry["op"])
    ref = _oracle_fn(entry)
    if ref is None:
        return []
    lo, hi = t.get("lo", -1.0), t.get("hi", 1.0)
    grad = t.get("grad", True)
    grad_rtol = t.get("grad_rtol")
    attrs = t.get("attrs") or {}
    kw = dict(attrs=attrs, grad_rtol=grad_rtol,
              rtol=t.get("rtol"), atol=t.get("atol"))
    if kind == "expr":
        # declarative inputs: {name: "<expression over the DSL>"}; grad may
        # be a LIST of input names (default: no grad check — most expr ops
        # are indexing/integer ops)
        ns = _expr_ns()
        inputs = {n: eval(src, dict(ns))  # noqa: S307 — in-repo schema
                  for n, src in (t.get("inputs") or {}).items()}
        gi = grad if isinstance(grad, list) else ([] if grad in (
            True, False) else [])
        return [op_case(op, ref, inputs, grad_inputs=gi,
                        out_index=t.get("out_index", 0), **kw)]
    kw["grad_inputs"] = None if grad else []
    if kind == "binary":
        shapes = [((3, 4), (3, 4)), ((2, 3, 4), (3, 4)), ((3, 1), (1, 4))]
        return [op_case(op, ref, {"x": _rand(sx, np.float32, lo, hi),
                                  "y": _rand(sy, np.float32, lo, hi)}, **kw)
                for sx, sy in shapes]
    if kind == "unary":
        n_extra = len(entry["args"]) - 1
        cases = []
        for s in [(3, 4), ()]:
            inputs = {"x": _rand(s, np.float32, lo, hi)}
            for i in range(n_extra):
                inputs[entry["args"][1 + i]] = _rand(s, np.float32, lo, hi)
            cases.append(op_case(op, ref, inputs, **kw))
        return cases
    if kind == "reduction":
        return [op_case(op, ref, {"x": _rand((3, 4), np.float32, lo, hi)},
                        **kw)]
    raise ValueError(f"unknown test kind {kind}")


_ALL = []
for _e in ENTRIES:
    for _i, _c in enumerate(_cases(_e)):
        _ALL.append(pytest.param(_c, _i == 0, id=f"{_e['op']}-{_i}"))


@pytest.mark.parametrize("case,check_grad", _ALL)
def test_op(case, check_grad):
    case.run(grad=check_grad)


def test_custom_vjp_matches_numeric():
    """The schema's custom-vjp entries must agree with finite differences
    (reference: backward.yaml grad kernels checked by check_grad)."""
    import jax
    import jax.numpy as jnp
    for name in [e["op"] for e in ENTRIES if e.get("vjp")]:
        op = getattr(gm, name)
        x = jnp.asarray(_rand((5,), np.float32, 0.5, 2.0))
        g = jax.grad(lambda v: op(v).sum())(x)
        eps = 1e-3
        fd = [(float(op(x.at[i].add(eps)).sum())
               - float(op(x.at[i].add(-eps)).sum())) / (2 * eps)
              for i in range(5)]
        np.testing.assert_allclose(np.asarray(g), fd, rtol=1e-2, atol=1e-3,
                                   err_msg=name)


def test_op_info_registry():
    assert gm.OP_INFO["sum"]["sharding"] == "reduction"
    assert gm.OP_INFO["add"]["sharding"] == "elementwise"
    assert gm.OP_INFO["addmm"]["sharding"] == "contraction"
    assert gm.OP_INFO["rsqrt"]["custom_vjp"]
    assert gm.OP_INFO["mean"]["attrs"] == {"axis": "None",
                                           "keepdim": "False"}
