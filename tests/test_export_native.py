"""jit.save/load (StableHLO artifacts), inference predictor, native
TCPStore + datafeed (csrc/), static save_inference_model veneer."""

import os

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.jit import InputSpec, load as jit_load, save as jit_save


def small_net():
    pp.seed(0)
    return pp.nn.Sequential(pp.nn.Linear(8, 16), pp.nn.GELU(),
                            pp.nn.Linear(16, 4))


class TestJitSaveLoad:
    def test_roundtrip(self, tmp_path):
        net = small_net()
        path = str(tmp_path / "model")
        jit_save(net, path, input_spec=[InputSpec([2, 8], "float32")])
        assert os.path.exists(path + ".pdmodel")
        assert os.path.exists(path + ".pdiparams.npz")
        assert os.path.exists(path + ".pdmeta")

        loaded = jit_load(path)
        x = pp.randn([2, 8])
        want = net(x).numpy()
        got = loaded(x)
        np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-6)

    def test_example_tensor_spec(self, tmp_path):
        net = small_net()
        path = str(tmp_path / "m2")
        jit_save(net, path, input_spec=[pp.randn([3, 8])])
        out = jit_load(path)(pp.randn([3, 8]))
        assert tuple(out.shape) == (3, 4)

    def test_missing_spec_raises(self, tmp_path):
        with pytest.raises(ValueError, match="input_spec"):
            jit_save(small_net(), str(tmp_path / "m3"))

    def test_static_veneer(self, tmp_path):
        from paddle_tpu.static import (load_inference_model,
                                       save_inference_model)
        net = small_net()
        path = str(tmp_path / "static_model")
        save_inference_model(path, [InputSpec([1, 8], "float32")], net)
        layer = load_inference_model(path)
        assert tuple(layer(pp.randn([1, 8])).shape) == (1, 4)


class TestInferencePredictor:
    def test_config_predictor_run(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        net = small_net()
        path = str(tmp_path / "served")
        jit_save(net, path, input_spec=[InputSpec([2, 8], "float32")])

        config = Config(path + ".pdmodel")
        config.switch_ir_optim(True)  # parity no-op
        pred = create_predictor(config)
        names = pred.get_input_names()
        assert len(names) == 1
        x = np.random.default_rng(0).normal(size=(2, 8)).astype("float32")
        pred.get_input_handle(names[0]).copy_from_cpu(x)
        outs = pred.run()
        want = net(pp.to_tensor(x)).numpy()
        np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-6)
        h = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(h.copy_to_cpu(), want, rtol=1e-5,
                                   atol=1e-6)


class TestNativeStore:
    def test_set_get_add_barrier(self):
        from paddle_tpu.distributed.tcp_store import TCPStore
        st = TCPStore("127.0.0.1", 29811, is_master=True, world_size=1,
                      timeout=20)
        try:
            st.set("k", b"v123")
            assert st.get("k") == b"v123"
            assert st.add("ctr", 3) == 3
            assert st.add("ctr", 4) == 7
            assert st.check("k")
            assert not st.check("missing")
            with pytest.raises(KeyError):
                st.get("missing", wait=False)
            st.wait("k")
            st.barrier()
        finally:
            st.close()

    def test_two_clients_share_state(self):
        from paddle_tpu.distributed.tcp_store import TCPStore
        master = TCPStore("127.0.0.1", 29812, is_master=True, world_size=2,
                          timeout=20)
        client = TCPStore("127.0.0.1", 29812, is_master=False,
                          world_size=2, timeout=20)
        try:
            master.set("addr", b"10.0.0.1:1234")
            assert client.get("addr") == b"10.0.0.1:1234"
            assert client.add("n", 1) == 1
            assert master.add("n", 1) == 2
        finally:
            client.close()
            master.close()


class TestNativeDataFeed:
    def test_batches_shapes_and_shift(self, tmp_path):
        from paddle_tpu.io.token_dataset import (TokenFileDataset,
                                                 write_token_file)
        path = str(tmp_path / "toks.bin")
        write_token_file(path, np.arange(5000, dtype=np.int32) % 97)
        ds = TokenFileDataset(path, seq_len=32, batch_size=4,
                              shuffle=False, epochs=1)
        try:
            batches = list(ds)
            assert len(batches) == ds.num_batches
            b0 = batches[0]
            assert b0["input_ids"].shape == (4, 32)
            np.testing.assert_array_equal(b0["input_ids"][:, 1:],
                                          b0["labels"][:, :-1])
            # unshuffled: first window starts at token 0
            assert b0["input_ids"][0, 0] == 0
        finally:
            ds.close()

    def test_shuffle_is_permutation(self, tmp_path):
        from paddle_tpu.io.token_dataset import (TokenFileDataset,
                                                 write_token_file)
        path = str(tmp_path / "toks2.bin")
        n_win, seq = 64, 15
        write_token_file(path,
                         np.arange(n_win * (seq + 1), dtype=np.int32))
        ds = TokenFileDataset(path, seq_len=seq, batch_size=4,
                              shuffle=True, seed=1, epochs=1)
        try:
            firsts = []
            for b in ds:
                firsts.extend(b["input_ids"][:, 0].tolist())
            # every window visited exactly once
            assert sorted(firsts) == [i * (seq + 1) for i in range(n_win)]
        finally:
            ds.close()

    def test_works_with_dataloader(self, tmp_path):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.token_dataset import (TokenFileDataset,
                                                 write_token_file)
        path = str(tmp_path / "toks3.bin")
        write_token_file(path, np.arange(4000, dtype=np.int32))
        ds = TokenFileDataset(path, seq_len=16, batch_size=8, epochs=1)
        try:
            # native feed already batches: batch_size=None passthrough
            loader = DataLoader(ds, batch_size=None)
            count = sum(1 for _ in loader)
            assert count == ds.num_batches
        finally:
            ds.close()
