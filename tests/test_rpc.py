"""paddle.distributed.rpc parity (VERDICT r3 Missing #7).

Reference: python/paddle/distributed/rpc/rpc.py (init_rpc, rpc_sync,
rpc_async, shutdown, worker infos over a C++ brpc agent).  Here the
agent is a threaded TCP server + native-TCPStore discovery
(distributed/rpc.py).
"""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.elastic import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "rpc_worker.py")


@pytest.fixture
def world1():
    rpc.init_rpc("solo", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{free_port()}")
    yield
    rpc.shutdown()


def _double(x):
    return 2 * x


class TestRpcSingleWorld:
    def test_sync_self_call(self, world1):
        assert rpc.rpc_sync("solo", _double, args=(21,)) == 42

    def test_async_future(self, world1):
        fut = rpc.rpc_async("solo", _double, args=(5,))
        assert fut.wait() == 10
        assert fut.done()

    def test_kwargs_and_exception(self, world1):
        assert rpc.rpc_sync("solo", int, args=("ff",),
                            kwargs={"base": 16}) == 255
        with pytest.raises(ZeroDivisionError):
            rpc.rpc_sync("solo", divmod, args=(1, 0))

    def test_worker_infos(self, world1):
        wi = rpc.get_worker_info("solo")
        assert wi.rank == 0 and wi.port > 0
        assert rpc.get_current_worker_info().name == "solo"
        assert [w.name for w in rpc.get_all_worker_infos()] == ["solo"]

    def test_unknown_worker_rejected(self, world1):
        with pytest.raises(ValueError, match="unknown rpc worker"):
            rpc.rpc_sync("nobody", _double, args=(1,))

    def test_double_init_rejected(self, world1):
        with pytest.raises(RuntimeError, match="twice"):
            rpc.init_rpc("again", rank=0, world_size=1,
                         master_endpoint="127.0.0.1:1")

    def test_uninitialized_rejected(self):
        with pytest.raises(RuntimeError, match="not initialized"):
            rpc.rpc_sync("solo", _double, args=(1,))


@pytest.mark.slow  # 2-process drill; CI multi-process gate runs it
def test_two_process_rpc(tmp_path):
    """Real 2-process RPC through the launch CLI: cross-process sync,
    async fan-out, and remote-exception propagation."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PADDLE_STORE_PORT"] = str(free_port())
    for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_MASTER"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{free_port()}",
         "--log_dir", str(tmp_path / "logs"), WORKER, str(tmp_path)],
        env=env, timeout=180, capture_output=True, text=True)
    logs = ""
    if (tmp_path / "logs").exists():
        for f in sorted((tmp_path / "logs").iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-3000:]
    assert proc.returncode == 0, f"rc={proc.returncode}\n{logs}"
    with open(tmp_path / "result.json") as f:
        result = json.load(f)
    assert result["got"] == 1024
    assert result["workers"] == ["worker0", "worker1"]
    assert result["self"] == "worker0"
