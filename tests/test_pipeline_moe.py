"""Pipeline (SPMD GPipe-via-scan) and MoE (GShard dispatch) tests.

Parity pattern from the reference test suite (SURVEY.md §4): the pipelined /
expert-parallel result must equal the serial numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.communication import shard_map

import paddle_tpu as pp
import paddle_tpu.distributed as dist


# -- segmentation / PipelineLayer API ----------------------------------------

class TestPipelineLayerAPI:
    def test_uniform_segmentation(self):
        seg = dist.SegmentLayers([object()] * 10, 4, "uniform")
        bounds = seg.do_segment()
        assert bounds[0] == 0 and bounds[-1] == 10
        sizes = np.diff(bounds)
        assert sizes.max() - sizes.min() <= 1

    def test_param_segmentation_balances(self):
        descs = [dist.LayerDesc(pp.nn.Linear, 4, 4) for _ in range(4)] + \
                [dist.LayerDesc(pp.nn.Linear, 64, 64) for _ in range(4)]
        seg = dist.SegmentLayers(descs, 2, "param")
        bounds = seg.do_segment()
        # big layers concentrated at the end: stage 0 takes most small ones
        assert bounds[1] >= 4

    def test_pipeline_layer_build_and_serial_forward(self):
        pp.seed(0)
        descs = [dist.LayerDesc(pp.nn.Linear, 8, 8) for _ in range(4)]
        pl = dist.PipelineLayer(descs, num_stages=2)
        x = pp.randn([2, 8])
        out = pl(x)
        ref = x
        for lin in pl.run_function:
            ref = lin(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy())
        assert len(pl.stage_layers(0)) == 2
        assert len(pl.stage_layers(1)) == 2

    def test_shared_layer_desc_ties_weights(self):
        descs = [
            dist.SharedLayerDesc("emb", pp.nn.Linear, 8, 8),
            dist.LayerDesc(pp.nn.Linear, 8, 8),
            dist.SharedLayerDesc("emb", pp.nn.Linear, 8, 8),
        ]
        pl = dist.PipelineLayer(descs, num_stages=3)
        layers = list(pl.run_function)
        assert layers[0] is layers[2]


# -- the SPMD schedule -------------------------------------------------------

def _stacked_linear_params(key, S, d):
    ws = jax.random.normal(key, (S, d, d)) * 0.3
    bs = jnp.zeros((S, d))
    return {"w": ws, "b": bs}


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


class TestSpmdPipeline:
    def _run(self, S, M, d=8, mb=4):
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        params = _stacked_linear_params(jax.random.PRNGKey(0), S, d)
        xs = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))

        @jax.jit
        def run(params, xs):
            def body(p_slice, x_all):
                p = jax.tree.map(lambda a: a[0], p_slice)  # drop staged dim
                out = dist.spmd_pipeline(_stage_fn, p, x_all,
                                         num_microbatches=M)
                # keep only last stage's buffer
                idx = jax.lax.axis_index("pp")
                out = jnp.where(idx == S - 1, out, jnp.zeros_like(out))
                return jax.lax.psum(out, "pp")

            return shard_map(body, mesh=mesh,
                             in_specs=(P("pp"), P()),
                             out_specs=P())(params, xs)

        got = run(params, xs)
        # serial oracle
        want = xs
        for s in range(S):
            p = jax.tree.map(lambda a: a[s], params)
            want = _stage_fn(p, want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_4stage_8microbatch(self):
        self._run(S=4, M=8)

    def test_8stage_4microbatch(self):
        self._run(S=8, M=4)

    def test_pipeline_grads_match_serial(self):
        S, M, d, mb = 4, 4, 6, 2
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        params = _stacked_linear_params(jax.random.PRNGKey(0), S, d)
        xs = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))

        def pipelined_loss(params, xs):
            def body(p_slice, x_all):
                p = jax.tree.map(lambda a: a[0], p_slice)
                out = dist.spmd_pipeline(_stage_fn, p, x_all,
                                         num_microbatches=M)
                idx = jax.lax.axis_index("pp")
                out = jnp.where(idx == S - 1, out, jnp.zeros_like(out))
                return jax.lax.psum((out ** 2).sum(), "pp")
            return shard_map(body, mesh=mesh, in_specs=(P("pp"), P()),
                             out_specs=P())(params, xs)

        def serial_loss(params, xs):
            h = xs
            for s in range(S):
                p = jax.tree.map(lambda a: a[s], params)
                h = _stage_fn(p, h)
            return (h ** 2).sum()

        g_pipe = jax.jit(jax.grad(pipelined_loss))(params, xs)
        g_ser = jax.grad(serial_loss)(params, xs)
        for k in g_ser:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_ser[k]),
                                       rtol=5e-4, atol=5e-5)

    def test_stack_stage_params(self):
        per_stage = [{"w": jnp.ones((2, 2)) * i} for i in range(3)]
        stacked = dist.stack_stage_params(per_stage)
        assert stacked["w"].shape == (3, 2, 2)
        np.testing.assert_allclose(np.asarray(stacked["w"][2]), 2.0)

    def test_shape_changing_stage_rejected(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("pp",))
        bad = lambda p, x: jnp.concatenate([x, x], -1)
        with pytest.raises(ValueError, match="shape-preserving"):
            shard_map(
                lambda xs: dist.spmd_pipeline(bad, None, xs,
                                              num_microbatches=2),
                mesh=mesh, in_specs=P(), out_specs=P())(jnp.ones((2, 2, 4)))


# -- MoE ---------------------------------------------------------------------

class TestGating:
    def test_top1_routes_to_argmax(self):
        logits = jnp.array([[5.0, 0.0, 0.0, 0.0],
                            [0.0, 5.0, 0.0, 0.0],
                            [0.0, 0.0, 5.0, 0.0]])
        combine, dispatch, aux = dist.top_k_gating(logits, k=1, capacity=2)
        # token i dispatched to expert i, slot 0
        for i in range(3):
            assert bool(dispatch[i, i, 0])
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        # all tokens want expert 0, capacity 2 -> only 2 dispatched
        logits = jnp.tile(jnp.array([[9.0, 0.0]]), (5, 1))
        combine, dispatch, aux = dist.top_k_gating(logits, k=1, capacity=2)
        assert int(dispatch[:, 0, :].sum()) == 2

    def test_top2_combine_normalised(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (16, 4))
        combine, dispatch, aux = dist.top_k_gating(logits, k=2, capacity=16)
        sums = np.asarray(combine.sum(axis=(1, 2)))
        np.testing.assert_allclose(sums, 1.0, atol=1e-5)


class TestMoELayer:
    def test_forward_shape_and_aux(self):
        pp.seed(0)
        moe = dist.MoELayer(d_model=8, num_experts=4, d_hidden=16,
                            gate="gshard", capacity_factor=2.0)
        x = pp.randn([2, 8, 8])
        out = moe(x)
        assert tuple(out.shape) == (2, 8, 8)
        assert np.isfinite(float(moe.aux_loss))

    def test_matches_dense_oracle_top1_big_capacity(self):
        """top-1, capacity >= tokens: every token goes to its argmax expert
        — output must equal running that expert's FFN on the token."""
        pp.seed(1)
        d, E = 4, 2
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=8,
                            gate="switch", capacity_factor=float(E * 4))
        moe.gate.jitter_eps = 0.0
        x = pp.randn([1, 6, d])
        out = moe(x)

        from paddle_tpu.core.dispatch import unwrap
        xd = unwrap(x).reshape(-1, d)
        logits = np.asarray(xd @ unwrap(moe.gate.gate))
        choice = logits.argmax(-1)
        w1 = np.asarray(unwrap(moe.experts.w1))
        w2 = np.asarray(unwrap(moe.experts.w2))
        b1 = np.asarray(unwrap(moe.experts.b1))
        b2 = np.asarray(unwrap(moe.experts.b2))
        probs = jax.nn.softmax(jnp.asarray(logits), -1)
        want = []
        for t in range(6):
            e = int(choice[t])
            h = np.asarray(jax.nn.gelu(
                jnp.asarray(np.asarray(xd)[t] @ w1[e] + b1[e])))
            y = (h @ w2[e] + b2[e]) * float(probs[t, e] / probs[t, e])
            want.append(y)
        want = np.stack(want).reshape(1, 6, d)
        np.testing.assert_allclose(out.numpy(), want, rtol=2e-4, atol=2e-4)

    def test_ep_sharded_jit_matches_serial(self):
        """Expert axis sharded over 8 devices == serial result."""
        pp.seed(2)
        d, E = 8, 8
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=16,
                            capacity_factor=4.0)
        x = pp.randn([2, 8, d])
        serial = moe(x).numpy()

        from paddle_tpu.core.functional import functional_call, params_of
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        params = params_of(moe)
        specs = {n: getattr(t, "partition_spec", P()) if
                 getattr(t, "partition_spec", None) is not None else P()
                 for n, t in moe.state_dict(keep_vars=True).items()}
        sharded = {n: jax.device_put(a, NamedSharding(mesh, specs[n]))
                   for n, a in params.items()}

        @jax.jit
        def f(ps, xd):
            out = functional_call(moe, ps, pp.Tensor(xd))
            return out._data

        with mesh:
            got = f(sharded, x._data)
        np.testing.assert_allclose(np.asarray(got), serial, rtol=2e-4,
                                   atol=2e-4)

    def test_dropless_never_drops(self):
        """Adversarial routing (every token to expert 0): capacity mode
        zeroes overflow tokens, dropless mode keeps them all."""
        pp.seed(4)
        d, E, T = 4, 4, 16
        for dropless, expect_zero_rows in [(False, True), (True, False)]:
            moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=8,
                                gate="switch", capacity_factor=0.25,
                                dropless=dropless)
            moe.gate.jitter_eps = 0.0
            # zero gate weight -> all logits tie at 0 -> argmax routes every
            # token to expert 0 (true adversarial all-to-one load)
            moe.gate.gate.set_value(pp.to_tensor(np.zeros((d, E), np.float32)))
            x = pp.randn([1, T, d])
            out = np.asarray(moe(x).numpy())
            zero_rows = (np.abs(out.reshape(T, d)).sum(-1) < 1e-9).sum()
            if expect_zero_rows:
                assert zero_rows > 0
            else:
                assert zero_rows == 0

    def test_a2a_matches_einsum_dropless(self):
        """all_to_all dispatch over an 8-way ep mesh == dense einsum
        dispatch, when dropless (no capacity drops on either path)."""
        pp.seed(5)
        d, E = 8, 8
        B, S = 4, 16  # 64 tokens, 8 per shard
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=16,
                            dropless=True, capacity_factor=999.0)
        x = pp.randn([B, S, d])
        serial = moe(x).numpy()

        from paddle_tpu.core.dispatch import unwrap
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        gate_w = unwrap(moe.gate.gate)
        w1, b1 = unwrap(moe.experts.w1), unwrap(moe.experts.b1)
        w2, b2 = unwrap(moe.experts.w2), unwrap(moe.experts.b2)

        @jax.jit
        def f(xd, gw, a1, c1, a2, c2):
            out, aux = dist.moe_forward_a2a(
                xd, gw, a1, c1, a2, c2, mesh=mesh, top_k=2, dropless=True,
                activation=lambda v: unwrap(moe.experts.activation(v)))
            return out, aux

        got, aux = f(x._data, gate_w, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(got), serial, rtol=2e-4,
                                   atol=2e-4)
        assert np.isfinite(float(aux))

    @pytest.mark.slow  # heavy 8-way a2a trace; CI SPMD suite runs it
    def test_a2a_layer_mode_and_grads(self):
        """MoELayer(dispatch_mode='all_to_all') trains: grads flow through
        router + experts under jit over the ep mesh."""
        pp.seed(6)
        d, E = 4, 8
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=8,
                            dispatch_mode="all_to_all", mesh=mesh,
                            dropless=True)
        from paddle_tpu.core.functional import functional_call, params_of
        params = params_of(moe)

        def loss(ps, xd):
            out = functional_call(moe, ps, pp.Tensor(xd))
            return (out._data ** 2).sum()

        x = np.random.default_rng(0).normal(size=(2, 8, d)).astype("float32")
        val, g = jax.value_and_grad(loss)(params, jnp.asarray(x))
        assert np.isfinite(float(val))
        gate_g = next(v for k, v in g.items() if "gate" in k)
        assert float(jnp.abs(gate_g).sum()) > 0
        expert_g = next(v for k, v in g.items() if k.endswith("w1"))
        assert float(jnp.abs(expert_g).sum()) > 0

    @pytest.mark.slow  # heavy 8-way a2a trace; CI SPMD suite runs it
    def test_a2a_index_matches_einsum_body(self):
        """Index-dispatch shard body == one-hot einsum shard body over the
        8-way ep mesh, with AND without capacity drops (both bodies share
        the top_k_gating_indices bookkeeping, so their drop sets are
        identical)."""
        pp.seed(7)
        d, E = 8, 8
        B, S = 4, 16
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=16)
        x = pp.randn([B, S, d])
        from paddle_tpu.core.dispatch import unwrap
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        args = (x._data, unwrap(moe.gate.gate), unwrap(moe.experts.w1),
                unwrap(moe.experts.b1), unwrap(moe.experts.w2),
                unwrap(moe.experts.b2))
        act = lambda v: unwrap(moe.experts.activation(v))
        for kw in (dict(dropless=True),
                   dict(dropless=False, capacity_factor=0.5)):
            ein, aux_e, drop_e = dist.moe_forward_a2a(
                *args, mesh=mesh, top_k=2, activation=act,
                with_stats=True, dispatch="einsum", **kw)
            idx, aux_i, drop_i = dist.moe_forward_a2a(
                *args, mesh=mesh, top_k=2, activation=act,
                with_stats=True, dispatch="index", **kw)
            np.testing.assert_allclose(np.asarray(idx), np.asarray(ein),
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(float(aux_i), float(aux_e), rtol=1e-5)
            np.testing.assert_allclose(float(drop_i), float(drop_e),
                                       atol=1e-6)
            if not kw.get("dropless"):
                assert float(drop_i) > 0  # the capacity bound actually bit

    @pytest.mark.slow  # heavy 8-way a2a trace; CI SPMD suite runs it
    def test_a2a_index_layer_mode_and_grads(self):
        """MoELayer(dispatch_mode='all_to_all_index') trains on the ep
        mesh: grads reach router and experts."""
        pp.seed(8)
        d, E = 4, 8
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=8,
                            dispatch_mode="all_to_all_index", mesh=mesh,
                            dropless=True)
        from paddle_tpu.core.functional import functional_call, params_of
        params = params_of(moe)

        def loss(ps, xd):
            out = functional_call(moe, ps, pp.Tensor(xd))
            return (out._data ** 2).sum()

        x = np.random.default_rng(0).normal(size=(2, 8, d)).astype("float32")
        val, g = jax.value_and_grad(loss)(params, jnp.asarray(x))
        assert np.isfinite(float(val))
        assert float(jnp.abs(next(v for k, v in g.items()
                                  if "gate" in k)).sum()) > 0
        assert float(jnp.abs(next(v for k, v in g.items()
                                  if k.endswith("w1"))).sum()) > 0

    def test_ragged_matches_einsum_dropless(self):
        """Sort + ragged_dot dropless dispatch == dense einsum dispatch
        with dropless capacity (same weights, same tokens)."""
        pp.seed(9)
        d, E = 8, 4
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=16,
                            dropless=True)
        x = pp.randn([2, 16, d])
        serial = moe(x).numpy()
        aux_serial = float(moe.aux_loss)

        from paddle_tpu.core.dispatch import unwrap
        x2d = unwrap(x).reshape(-1, d)
        logits = x2d @ unwrap(moe.gate.gate)
        out, aux, dropped = dist.moe_forward_ragged(
            x2d, logits, unwrap(moe.experts.w1), unwrap(moe.experts.b1),
            unwrap(moe.experts.w2), unwrap(moe.experts.b2), E=E, top_k=2,
            activation=lambda v: unwrap(moe.experts.activation(v)))
        np.testing.assert_allclose(np.asarray(out).reshape(2, 16, d),
                                   serial, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(float(aux), aux_serial, rtol=1e-5)
        assert float(dropped) == 0.0

    def test_ragged_layer_mode_and_grads(self):
        """MoELayer(dispatch_mode='ragged') under jit: grads reach router
        and experts (ragged_dot + scatter-add transposes)."""
        pp.seed(10)
        moe = dist.MoELayer(d_model=4, num_experts=4, d_hidden=8,
                            dispatch_mode="ragged")
        from paddle_tpu.core.functional import functional_call, params_of
        params = params_of(moe)

        def loss(ps, xd):
            out = functional_call(moe, ps, pp.Tensor(xd))
            return (out._data ** 2).sum()

        x = np.random.default_rng(1).normal(size=(2, 8, 4)).astype("float32")
        val, g = jax.value_and_grad(jax.jit(loss))(params, jnp.asarray(x))
        assert np.isfinite(float(val))
        assert float(jnp.abs(g["gate.gate"]).sum()) > 0
        assert float(jnp.abs(g["experts.w1"]).sum()) > 0

    def test_grads_flow_through_router_in_jit(self):
        pp.seed(3)
        moe = dist.MoELayer(d_model=4, num_experts=2, d_hidden=8,
                            capacity_factor=4.0)
        from paddle_tpu.core.functional import functional_call, params_of
        params = params_of(moe)

        def loss(ps, xd):
            out = functional_call(moe, ps, pp.Tensor(xd))
            return (out._data ** 2).sum()

        x = np.random.default_rng(0).normal(size=(1, 4, 4)).astype("float32")
        g = jax.grad(loss)(params, jnp.asarray(x))
        assert float(jnp.abs(g["gate.gate"]).sum()) > 0
        assert float(jnp.abs(g["experts.w1"]).sum()) > 0


# -- pp x dp x ep: MoE (shared + routed experts) inside the 1F1B pipeline ----

class TestMoEPipeline3D:
    """VERDICT r2 item 6 'done' criterion: an MoE block with SHARED
    experts trains inside PipelineTrainStep on a (pp, dp, ep) mesh with
    parity vs the serial dense-routed oracle (dropless, so capacity
    semantics cannot diverge)."""

    S, DP, EP, M = 2, 2, 2, 4
    d, hid, E, K = 8, 16, 4, 2
    mbs, T = 4, 3

    def _params(self, key):
        S, d, hid, E = self.S, self.d, self.hid, self.E
        ks = jax.random.split(key, 9)
        s = 1 / np.sqrt(d)
        return {
            "wproj": jax.random.normal(ks[0], (S, d, d)) * s,
            "gate": jax.random.normal(ks[1], (S, d, E)) * s,
            "ew1": jax.random.normal(ks[2], (S, E, d, hid)) * s,
            "eb1": jnp.zeros((S, E, hid)),
            "ew2": jax.random.normal(ks[3], (S, E, hid, d)) * s,
            "eb2": jnp.zeros((S, E, d)),
            "sw1": jax.random.normal(ks[4], (S, d, hid)) * s,
            "sw2": jax.random.normal(ks[5], (S, hid, d)) * s,
        }

    @staticmethod
    def _stage_fn(p, x):
        sq = lambda a: a[0]
        mbs, s, d = x.shape
        h = jnp.tanh(jnp.einsum("bsd,de->bse", x, sq(p["wproj"])))
        # shared expert: always-on dense ffn
        shared = jnp.einsum("bsh,hd->bsd",
                            jax.nn.gelu(jnp.einsum("bsd,dh->bsh", h,
                                                   sq(p["sw1"]))),
                            sq(p["sw2"]))
        x2d = h.reshape(-1, d)
        routed, aux, dropped = dist.moe_shard_a2a(
            x2d, sq(p["gate"]), sq(p["ew1"]), sq(p["eb1"]),
            sq(p["ew2"]), sq(p["eb2"]), top_k=2,
            capacity=x2d.shape[0])  # dropless: capacity == local tokens
        return x + shared + routed.reshape(mbs, s, d)

    @staticmethod
    def _first_fn(p, raw):
        return raw @ p["win"]

    @staticmethod
    def _last_fn(p, y, lab):
        return jnp.mean((jnp.einsum("bsd,do->bso", y, p["wout"]) - lab) ** 2)

    def _serial(self, ps, first, last, mb_in, mb_lab):
        """Dense-routed oracle: per-token top-k over global softmax, the
        exact math dropless dispatch computes."""
        S, E, K = self.S, self.E, self.K

        def moe_tok(p_s, h2d):
            probs = jax.nn.softmax(h2d @ p_s["gate"], axis=-1)
            topv, topi = jax.lax.top_k(probs, K)
            w = topv / jnp.sum(topv, -1, keepdims=True)
            outs = []
            for e in range(E):
                he = jax.nn.gelu(h2d @ p_s["ew1"][e] + p_s["eb1"][e])
                outs.append(he @ p_s["ew2"][e] + p_s["eb2"][e])
            outs = jnp.stack(outs, 1)                    # [T, E, d]
            sel = jax.nn.one_hot(topi, E)                # [T, K, E]
            return jnp.einsum("tk,tke,ted->td", w, sel, outs)

        def stage(p_s, x):
            mbs, s, d = x.shape
            h = jnp.tanh(jnp.einsum("bsd,de->bse", x, p_s["wproj"]))
            shared = jnp.einsum(
                "bsh,hd->bsd",
                jax.nn.gelu(jnp.einsum("bsd,dh->bsh", h, p_s["sw1"])),
                p_s["sw2"])
            routed = moe_tok(p_s, h.reshape(-1, d)).reshape(mbs, s, d)
            return x + shared + routed

        def one(m):
            x = mb_in[m] @ first["win"]
            for s_i in range(S):
                x = stage(jax.tree.map(lambda a: a[s_i], ps), x)
            return jnp.mean((jnp.einsum("bsd,do->bso", x, last["wout"])
                             - mb_lab[m]) ** 2)

        return sum(one(m) for m in range(self.M)) / self.M

    @pytest.mark.slow  # heavy 3D pp x dp x ep run; CI SPMD suite runs it
    def test_pp_dp_ep_parity_and_training(self):
        S, DP, EP, M = self.S, self.DP, self.EP, self.M
        d = self.d
        devs = np.array(jax.devices("cpu")[:S * DP * EP]).reshape(S, DP, EP)
        mesh = Mesh(devs, ("pp", "dp", "ep"))
        params = self._params(jax.random.PRNGKey(0))
        ks = jax.random.split(jax.random.PRNGKey(7), 2)
        first = {"win": jax.random.normal(ks[0], (5, d)) * 0.5}
        last = {"wout": jax.random.normal(ks[1], (d, 3)) * 0.5}
        specs = {
            "wproj": P("pp"), "gate": P("pp"),
            "ew1": P("pp", "ep"), "eb1": P("pp", "ep"),
            "ew2": P("pp", "ep"), "eb2": P("pp", "ep"),
            "sw1": P("pp"), "sw2": P("pp"),
        }
        rng = np.random.default_rng(0)
        mb_in = jnp.asarray(rng.standard_normal(
            (M, self.mbs, self.T, 5)), jnp.float32)
        mb_lab = jnp.asarray(rng.standard_normal(
            (M, self.mbs, self.T, 3)), jnp.float32)

        opt = pp.optimizer.SGD(learning_rate=0.05)
        step = dist.PipelineTrainStep(
            self._stage_fn, self._first_fn, self._last_fn, params, opt,
            mesh, M, specs, first_params=first,
            first_specs={"win": P()}, last_params=last,
            last_specs={"wout": P()}, remat=True, extra_data_axes=("ep",))

        want0 = float(self._serial(params, first, last, mb_in, mb_lab))
        loss0 = float(step({"inputs": mb_in, "labels": mb_lab}))
        np.testing.assert_allclose(loss0, want0, rtol=1e-4)

        # one-step param parity vs serial SGD on a routed expert weight
        g = jax.grad(lambda ps: self._serial(ps, first, last, mb_in,
                                             mb_lab))(params)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(step.params["ew1"])),
            np.asarray(params["ew1"] - 0.05 * g["ew1"]),
            rtol=5e-3, atol=1e-5)

        losses = [loss0]
        for _ in range(4):
            losses.append(float(step({"inputs": mb_in, "labels": mb_lab})))
        assert losses[-1] < losses[0], losses


class TestIndexDispatch:
    """Gather/scatter dispatch mode (moe_forward_index): O(T·k·d) instead
    of the dense [T,E,C] contraction — parity vs the einsum path."""

    def _pair(self, gate="gshard", cf=4.0, top_k=None, seed=3):
        pp.seed(seed)
        kw = dict(d_model=8, num_experts=4, d_hidden=16, gate=gate,
                  capacity_factor=cf)
        if top_k is not None:
            kw["top_k"] = top_k
        a = dist.MoELayer(dispatch_mode="einsum", **kw)
        b = dist.MoELayer(dispatch_mode="index", **kw)
        b.gate.gate._set_data(a.gate.gate._data)
        for n in ("w1", "b1", "w2", "b2"):
            getattr(b.experts, n)._set_data(getattr(a.experts, n)._data)
        if hasattr(a.gate, "jitter_eps"):
            a.gate.jitter_eps = b.gate.jitter_eps = 0.0
        return a, b

    def test_index_matches_einsum(self):
        a, b = self._pair()
        x = pp.randn([2, 8, 8])
        np.testing.assert_allclose(b(x).numpy(), a(x).numpy(),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(b.aux_loss), float(a.aux_loss),
                                   rtol=1e-5)

    def test_index_matches_einsum_under_capacity_pressure(self):
        a, b = self._pair(cf=0.5)          # forces drops
        x = pp.randn([2, 16, 8])
        np.testing.assert_allclose(b(x).numpy(), a(x).numpy(),
                                   rtol=2e-5, atol=2e-5)
        assert b.router_stats["dropped_frac"] > 0

    def test_index_grads_flow(self):
        """Training through the index dispatch: grads reach gate + experts."""
        import jax
        from paddle_tpu.core.functional import functional_call, params_of
        _, b = self._pair()
        params = params_of(b)
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(2, 8, 8)).astype(np.float32))

        def loss(ps):
            out = functional_call(b, ps, pp.Tensor(x))
            from paddle_tpu.core.dispatch import unwrap
            return jnp.sum(unwrap(out) ** 2)

        g = jax.jit(jax.grad(loss))(params)
        norms = [float(jnp.abs(v).sum()) for v in jax.tree.leaves(g)]
        assert all(np.isfinite(n) for n in norms)
        assert sum(n > 0 for n in norms) >= 4  # gate + w1/w2/b1(b2 maybe 0)

    def test_moe_config_dispatch_mode_wires_through(self):
        from paddle_tpu.models import MoEConfig, MoEForCausalLM
        cfg = MoEConfig.tiny()
        cfg.dispatch_mode = "index"
        m = MoEForCausalLM(cfg)
        assert m.model.layers[1].moe.dispatch_mode == "index"
