"""Autoshard planner: propagation fixed-point, candidate enumeration/
pruning, scorer monotonicity, collective cost model, peak-HBM helper,
plan-beats-manual on the 8-device llama harness, and determinism of the
emitted plan.  Everything runs on the virtual 8-CPU-device mesh the
conftest forces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pp
import paddle_tpu.analysis as analysis
from paddle_tpu.analysis import autoshard
from paddle_tpu.analysis.autoshard.candidates import (MeshCandidate,
                                                      enumerate_candidates,
                                                      specs_for_candidate)
from paddle_tpu.analysis.autoshard.propagation import (Collective,
                                                       Propagator,
                                                       norm_spec)
from paddle_tpu.analysis.passes.cost_model import (LINK_BANDWIDTH,
                                                   collective_seconds)
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _aval(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


# ------------------------------------------------ collective cost model

class TestCollectiveSeconds:
    def test_ring_formulas(self):
        bw = LINK_BANDWIDTH["ici"]
        n, k = 8e9, 8
        ag = collective_seconds("all_gather", n, k)
        rs = collective_seconds("reduce_scatter", n, k)
        ar = collective_seconds("all_reduce", n, k)
        a2a = collective_seconds("all_to_all", n, k)
        assert ag == pytest.approx((k - 1) / k * n / bw)
        assert rs == ag
        assert ar == pytest.approx(2 * ag)           # RS + AG
        assert a2a == pytest.approx(ag / k)
        assert collective_seconds("p2p", n, k) == pytest.approx(n / bw)

    def test_degenerate_cases(self):
        assert collective_seconds("all_gather", 1e9, 1) == 0.0
        assert collective_seconds("all_reduce", 0, 8) == 0.0

    def test_custom_bandwidth_and_link(self):
        fast = collective_seconds("all_gather", 1e9, 4, bandwidth=1e12)
        slow = collective_seconds("all_gather", 1e9, 4, link="dcn")
        assert fast < collective_seconds("all_gather", 1e9, 4) < slow

    def test_unknown_op_raises(self):
        with pytest.raises(ValueError):
            collective_seconds("gossip", 1e9, 4)

    def test_collective_record_seconds(self):
        c = Collective("all_reduce", 1000, ("tp",), count=3)
        assert c.seconds({"tp": 4}) == pytest.approx(
            3 * collective_seconds("all_reduce", 1000, 4))
        assert c.total_bytes == 3000


# ------------------------------------------------ propagation engine

class TestPropagation:
    def test_matched_contraction_partial_allreduce(self):
        closed = jax.make_jaxpr(lambda x, w: x @ w)(
            _aval((8, 16)), _aval((16, 32)))
        prop = Propagator({"x": 2}, track_cost=True)
        prop.run(closed.jaxpr, [norm_spec(P(None, "x"), 2),
                                norm_spec(P("x", None), 2)])
        kinds = [c.kind for c in prop.collectives]
        assert kinds == ["all_reduce"]
        # contraction split 2-ways: flops halve
        assert prop.eff_flops == pytest.approx(2 * 8 * 32 * 16 / 2)

    def test_mismatched_contraction_allgather(self):
        closed = jax.make_jaxpr(lambda x, w: x @ w)(
            _aval((8, 16)), _aval((16, 32)))
        prop = Propagator({"x": 2})
        prop.run(closed.jaxpr, [None, norm_spec(P("x", None), 2)])
        assert [c.kind for c in prop.collectives] == ["all_gather"]
        assert prop.collectives[0].bytes == 16 * 32 * 4   # full weight

    def test_scan_carry_fixed_point_and_weighting(self):
        def f(x, ws):
            def body(c, w):
                return c @ w, ()
            out, _ = jax.lax.scan(body, x, ws)
            return out

        closed = jax.make_jaxpr(f)(_aval((8, 16)), _aval((4, 16, 16)))
        prop = Propagator({"x": 2})
        outs = prop.run(closed.jaxpr,
                        [norm_spec(P(None, "x"), 2),
                         norm_spec(P(None, "x", None), 3)])
        # carry [8,16] starts sharded on dim1 but the matmul output is
        # replicated, so the fixed point settles on a replicated carry —
        # every iteration then all-gathers the dim0-sharded weight: ONE
        # record weighted by the scan length
        ags = [c for c in prop.collectives if c.kind == "all_gather"]
        assert ags and ags[0].count == 4
        assert ags[0].bytes == 16 * 16 * 4
        # carry placement is defined (loop-invariant) after the loop
        assert outs[0] is not None

    def test_scan_carry_converges_to_agreement(self):
        # carry sharded in, body re-shards it via matmul free dims —
        # the fixed point must settle (conflicting dims drop to None)
        def f(x, w):
            def body(c, _):
                return c @ w, ()
            out, _ = jax.lax.scan(body, x, jnp.arange(3))
            return out

        closed = jax.make_jaxpr(f)(_aval((8, 8)), _aval((8, 8)))
        prop = Propagator({"x": 2})
        outs = prop.run(closed.jaxpr,
                        [norm_spec(P("x", None), 2),
                         norm_spec(P(None, None), 2)])
        assert outs[0] is not None        # terminated, placement defined

    def test_while_carry(self):
        def f(x):
            return jax.lax.while_loop(
                lambda c: jnp.sum(c) < 100.0, lambda c: c * 2.0, x)

        closed = jax.make_jaxpr(f)(_aval((8, 4)))
        prop = Propagator({"x": 2})
        outs = prop.run(closed.jaxpr, [norm_spec(P("x", None), 2)])
        assert outs[0] == (("x",), None)

    def test_reshape_split_and_merge(self):
        def f(x):
            y = x.reshape(8, 4, 16)        # split dim0
            return y.reshape(32, 16)       # merge back

        closed = jax.make_jaxpr(f)(_aval((32, 16)))
        prop = Propagator({"dp": 4})
        outs = prop.run(closed.jaxpr, [norm_spec(P("dp", None), 2)])
        assert outs[0] == (("dp",), None)

    def test_backward_fill_through_constraint(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("mp",))
        from jax.sharding import NamedSharding

        def f(x):
            y = x * 2.0
            return jax.lax.with_sharding_constraint(
                y, NamedSharding(mesh, P("mp", None)))

        closed = jax.make_jaxpr(f)(_aval((8, 4)))
        prop = Propagator({"mp": 2}, track_cost=True)
        outs = prop.run(closed.jaxpr, [None])
        assert outs[0] == (("mp",), None)
        # backward seeded the producer: the mul is charged as sharded
        assert prop.eff_flops < 8 * 4

    def test_elementwise_conflict_records_reshard(self):
        def f(a, b):
            return a + b

        closed = jax.make_jaxpr(f)(_aval((8, 8)), _aval((8, 8)))
        diags = []
        prop = Propagator({"x": 2, "y": 2}, diags=diags)
        prop.run(closed.jaxpr, [norm_spec(P("x", None), 2),
                                norm_spec(P("y", None), 2)])
        assert any("conflicting shardings" in d.message for d in diags)
        assert any(c.kind == "all_to_all" for c in prop.collectives)

    def test_reduction_over_sharded_dim_is_allreduce(self):
        closed = jax.make_jaxpr(lambda x: jnp.sum(x, axis=0))(
            _aval((8, 4)))
        prop = Propagator({"x": 2})
        outs = prop.run(closed.jaxpr, [norm_spec(P("x", None), 2)])
        assert [c.kind for c in prop.collectives] == ["all_reduce"]
        assert outs[0] == (None,)

    def test_size_one_axis_is_noop(self):
        # a "collective" over a one-device axis must produce neither a
        # record nor a diagnostic (planner-degraded layouts hit this)
        closed = jax.make_jaxpr(lambda x, w: x @ w)(
            _aval((8, 16)), _aval((16, 32)))
        diags = []
        prop = Propagator({"fsdp": 1}, diags=diags)
        prop.run(closed.jaxpr, [None, norm_spec(P("fsdp", None), 2)])
        assert not prop.collectives and not diags

    def test_pallas_call_passthrough(self):
        pl = pytest.importorskip("jax.experimental.pallas")

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def f(x):
            return pl.pallas_call(
                kernel, out_shape=jax.ShapeDtypeStruct((8, 128),
                                                       jnp.float32),
                interpret=True)(x)

        closed = jax.make_jaxpr(f)(_aval((8, 128)))
        prims = {e.primitive.name for e in closed.jaxpr.eqns}
        if "pallas_call" not in prims:
            pytest.skip("pallas_call not traced on this backend")
        diags = []
        prop = Propagator({"dp": 2}, diags=diags)
        outs = prop.run(closed.jaxpr, [norm_spec(P("dp", None), 2)])
        assert outs[0] == (("dp",), None)     # adopted, not invented
        assert not diags


# ------------------------------------------------ candidates

class TestCandidates:
    def test_factorizations_cover_8(self):
        cands = list(enumerate_candidates(8))
        labels = {c.label for c in cands}
        assert "dp8xfsdp1xtp1" in labels
        assert "dp1xfsdp8xtp1" in labels
        assert "dp1xfsdp1xtp8" in labels
        assert "dp2xfsdp2xtp2" in labels
        # sp variants only for tp > 1
        assert "dp2xfsdp2xtp2+sp" in labels
        assert not any(c.seq_parallel and c.tp == 1 for c in cands)
        assert all(c.n_devices == 8 for c in cands)

    def test_pp_enumeration(self):
        cands = list(enumerate_candidates(8, max_pp=2))
        assert any(c.pp == 2 for c in cands)
        assert all(c.n_devices == 8 for c in cands)

    def test_sp_respects_seq_divisibility(self):
        cands = list(enumerate_candidates(8, seq_len=6))
        sp = [c for c in cands if c.seq_parallel]
        assert all(c.tp in (2,) or 6 % c.tp == 0 for c in sp)
        assert not any(c.tp == 4 and c.seq_parallel for c in cands)

    def test_batch_indivisible_prunes(self):
        cand = MeshCandidate(dp=4, fsdp=2, tp=1)
        _, why = specs_for_candidate(cand, {"w": (8, 8)},
                                     batch_shape=(6, 16))
        assert why and "not divisible" in why

    def test_indivisible_param_degrades_to_replicated(self):
        cand = MeshCandidate(dp=1, fsdp=2, tp=4)
        specs, why = specs_for_candidate(
            cand, {"x.q_proj.weight": (8, 6)}, batch_shape=(8, 16))
        assert why is None
        # out dim 6 % tp=4 → tp dropped; in dim 8 % fsdp=2 ok → kept
        assert specs["x.q_proj.weight"] == P("fsdp", None)

    def test_llama_template_matches_handwritten(self):
        cand = MeshCandidate(dp=2, fsdp=2, tp=2)
        specs, _ = specs_for_candidate(
            cand, {"model.layers.0.self_attn.q_proj.weight": (64, 64),
                   "model.embed_tokens.weight": (512, 64),
                   "model.norm.weight": (64,)})
        assert specs["model.layers.0.self_attn.q_proj.weight"] == \
            P("fsdp", "tp")
        assert specs["model.embed_tokens.weight"] == P("tp", "fsdp")
        assert specs["model.norm.weight"] == P()


# ------------------------------------------------ scorer

class TestScorerMonotonicity:
    def _trace(self):
        def f(x, w):
            return jnp.sum(x @ w)
        return analysis.trace(f, _aval((64, 256)), _aval((256, 512)),
                              param_specs={})

    def test_tp_trades_flops_for_allgather(self):
        tr = self._trace()
        base, _ = autoshard.score_layout(
            tr, {"arg1": P()}, {"dp": 1, "fsdp": 1, "tp": 4})
        tp, _ = autoshard.score_layout(
            tr, {"arg1": P(None, "tp")}, {"dp": 1, "fsdp": 1, "tp": 4})
        # column-parallel: per-device flops shrink...
        assert tp.compute_s < base.compute_s
        # ...but the zero-collective base stays zero while fsdp-style
        # gathers appear once the weight is sharded on the contraction
        zero3, _ = autoshard.score_layout(
            tr, {"arg1": P("fsdp", None)}, {"dp": 1, "fsdp": 4, "tp": 1})
        assert base.collective_bytes == 0
        assert zero3.collective_bytes > 0          # weight all-gather

    def test_dp_scales_compute_down(self):
        tr = self._trace()
        one, _ = autoshard.score_layout(
            tr, {}, {"dp": 1, "fsdp": 1, "tp": 1}, P(("dp", "fsdp")))
        eight, _ = autoshard.score_layout(
            tr, {}, {"dp": 8, "fsdp": 1, "tp": 1}, P(("dp", "fsdp")))
        assert eight.compute_s < one.compute_s
        assert eight.memory_s < one.memory_s


# ------------------------------------------------ peak-HBM helper

class TestEstimatePeakHbm:
    def test_plain_fn_sharding_shrinks_arguments(self):
        from paddle_tpu.distributed.planner import estimate_peak_hbm
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("dp", "tp"))

        def f(x, w):
            return x @ w

        x = _aval((64, 1024))
        w = _aval((1024, 1024))
        rep = estimate_peak_hbm(f, [None, None], mesh, x, w)
        shard = estimate_peak_hbm(f, [P("dp", None), P(None, "tp")],
                                  mesh, x, w)
        assert rep > 0 and shard > 0
        assert shard < rep


# ------------------------------------------------ llama 8-device harness

@pytest.fixture(scope="module")
def llama_step():
    pp.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = {"input_ids": jax.ShapeDtypeStruct((8, 16), jnp.int32),
             "labels": jax.ShapeDtypeStruct((8, 16), jnp.int32)}
    return cfg, model, step, batch


class TestPlanLlama:
    def test_plan_beats_or_ties_manual(self, llama_step):
        cfg, model, step, batch = llama_step
        manual = LlamaForCausalLM.partition_specs(cfg, fsdp_axis="fsdp")
        res = autoshard.plan(step, batch, n_devices=8,
                             manual_specs=manual,
                             manual_mesh_shape={"dp": 2, "fsdp": 2,
                                                "tp": 2})
        assert res.plans
        assert res.manual is not None
        assert res.beats_manual() is True
        assert res.top.score.step_seconds <= res.manual.step_seconds

    def test_emitted_plans_roundtrip_checker_clean(self, llama_step):
        _, _, step, batch = llama_step
        res = autoshard.plan(step, batch, n_devices=8, topk=3)
        for p in res.plans:
            rep = p.verify(step, batch)
            assert not rep.errors() and not rep.warnings(), (
                p.candidate.label + "\n" + rep.format())

    def test_plan_is_deterministic(self, llama_step):
        _, _, step, batch = llama_step
        a = autoshard.plan(step, batch, n_devices=8)
        b = autoshard.plan(step, batch, n_devices=8)
        assert a.top.candidate == b.top.candidate
        assert a.top.score.step_seconds == b.top.score.step_seconds
        assert a.top.param_specs == b.top.param_specs
        assert [s.candidate.label for s in a.scored] == \
            [s.candidate.label for s in b.scored]

    def test_table_renders(self, llama_step):
        _, _, step, batch = llama_step
        res = autoshard.plan(step, batch, n_devices=8)
        t = res.table()
        assert "pred ms" in t and "<- emit" in t

    @pytest.mark.slow
    def test_plan_runs_through_trainstep_shardings(self, llama_step):
        cfg, model, step, batch = llama_step
        res = autoshard.plan(step, batch, n_devices=8)
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        planned = TrainStep(model, opt, shardings=res.top)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (8, 17))
        l0 = planned({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
        l1 = planned({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
        assert np.isfinite(float(l0)) and float(l1) < float(l0)

    def test_hbm_budget_prunes(self, llama_step):
        _, _, step, batch = llama_step
        res = autoshard.plan(step, batch, n_devices=8, hbm_gb=1e-6)
        assert not res.plans
        assert all(s.pruned for s in res.scored)


class TestShardingsArg:
    def test_trainstep_rejects_pp_plan(self, llama_step):
        cfg, model, step, batch = llama_step
        res = autoshard.plan(step, batch, n_devices=8, max_pp=2, topk=20)
        pp_plans = [p for p in res.plans if p.is_pipeline]
        if not pp_plans:
            pytest.skip("no pipeline plan in top-k")
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        with pytest.raises(ValueError, match="PipelineTrainStep"):
            TrainStep(model, opt, shardings=pp_plans[0])

    def test_trainstep_shardings_dict(self, llama_step):
        cfg, model, _, _ = llama_step
        from jax.sharding import NamedSharding
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("dp", "fsdp", "tp"))
        rules = LlamaForCausalLM.partition_specs(cfg, fsdp_axis="fsdp")
        sh = {n: NamedSharding(mesh, LlamaForCausalLM.spec_for(n, rules))
              for n in model.state_dict(keep_vars=True)}
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, shardings=sh)
        assert step.mesh is mesh or step._param_sh is not None
        rng = np.random.default_rng(1)
        ids = rng.integers(0, cfg.vocab_size, (8, 17))
        loss = step({"input_ids": ids[:, :-1], "labels": ids[:, 1:]})
        assert np.isfinite(float(loss))

    def test_shardings_bad_type_raises(self, llama_step):
        cfg, model, _, _ = llama_step
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        with pytest.raises(TypeError):
            TrainStep(model, opt, shardings=42)

    def test_to_static_with_plan(self, llama_step):
        cfg, model, step, batch = llama_step
        from paddle_tpu.jit import to_static
        res = autoshard.plan(step, batch, n_devices=8)
        fn = to_static(model, shardings=res.top)
        ids = pp.Tensor(np.zeros((8, 16), np.int32))
        out = fn(ids)
        ref = to_static(model)(ids)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(ref._data), atol=2e-4)


class TestAutoshardPass:
    def test_registered_and_reports_current_layout(self, llama_step):
        cfg, model, _, batch = llama_step
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("dp", "fsdp", "tp"))
        rules = LlamaForCausalLM.partition_specs(cfg, fsdp_axis="fsdp")
        specs = {n: LlamaForCausalLM.spec_for(n, rules)
                 for n in model.state_dict(keep_vars=True)}
        opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
        from jax.sharding import PartitionSpec
        step = TrainStep(model, opt, mesh=mesh, param_specs=specs,
                         batch_spec=PartitionSpec(("dp", "fsdp")))
        rep = analysis.check(step, batch, passes=["autoshard"],
                             options={"autoshard_search": 8})
        msgs = [d.message for d in rep.by_pass("autoshard")]
        assert any("current layout" in m for m in msgs)
        assert any("best 8-device layout" in m for m in msgs)
        assert "autoshard_plans" in rep.extras
        assert rep.extras["autoshard_current"].step_seconds > 0

    def test_not_in_default_pipeline(self):
        from paddle_tpu.analysis.passes import DEFAULT_PASSES, get_pass
        assert "autoshard" not in DEFAULT_PASSES
        assert get_pass("autoshard") is not None


class TestAutoshardCLI:
    def test_cli_plans_and_beats_manual(self, capsys):
        from paddle_tpu.analysis.lint import main
        rc = main(["paddle_tpu.models.llama:LlamaForCausalLM",
                   "--init", "LlamaConfig.tiny()",
                   "--spec", "int32[8,16]",
                   "--autoshard", "--mesh-devices", "8",
                   "--assert-beats-manual"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "ranked plans" in out
        assert "round-trip: clean" in out
        assert "planner wins or ties" in out


# ------------------------------------------------ expert-parallel axis

class TestExpertAxis:
    """ISSUE 18 layer 3: ``ep`` in the candidate space — gated on
    stacked experts, dispatch a2a charged by the overlap-aware
    collective model, emitted plans round-trip the checker clean."""

    def _moe(self, d=64, E=8, h=128):
        import paddle_tpu.distributed as dist
        pp.seed(0)
        return dist.MoELayer(d_model=d, num_experts=E, d_hidden=h,
                             capacity_factor=2.0)

    def test_enumeration_gated_on_experts(self):
        dense = list(enumerate_candidates(8))
        assert not any(c.ep > 1 for c in dense)
        moe = list(enumerate_candidates(8, num_experts=8))
        eps = [c for c in moe if c.ep > 1]
        assert eps
        assert all(c.n_devices == 8 for c in moe)
        assert all(8 % c.ep == 0 for c in eps)
        labels = {c.label for c in moe}
        assert "dp1xfsdp1xtp1xep8" in labels
        assert "dp2xfsdp2xtp1xep2" in labels

    def test_ep_must_divide_expert_count(self):
        cands = list(enumerate_candidates(8, num_experts=6))
        assert {c.ep for c in cands} == {1, 2}   # 4, 8 do not divide 6

    def test_stacked_expert_template(self):
        cand = MeshCandidate(dp=1, fsdp=2, tp=2, ep=2)
        specs, why = specs_for_candidate(
            cand, {"experts.w1": (8, 64, 128), "experts.b1": (8, 128),
                   "experts.w2": (8, 128, 64), "experts.b2": (8, 64),
                   "gate.gate": (64, 8)},
            batch_shape=(8, 16))
        assert why is None
        assert specs["experts.w1"] == P("ep", "fsdp", "tp")
        assert specs["experts.b1"] == P("ep", "tp")
        assert specs["experts.w2"] == P("ep", "tp", "fsdp")
        assert specs["experts.b2"] == P("ep", "fsdp")
        assert specs["gate.gate"] == P()

    def test_ep_axis_degrades_on_dense_mesh(self):
        """A stacked-expert name scored on an ep-less candidate must not
        leak the ep axis into the spec."""
        cand = MeshCandidate(dp=2, fsdp=2, tp=2)
        specs, _ = specs_for_candidate(
            cand, {"experts.w1": (8, 64, 128)}, batch_shape=(8, 16))
        assert specs["experts.w1"] == P(None, "fsdp", "tp")

    def test_batch_shards_over_ep(self):
        cand = MeshCandidate(dp=2, fsdp=1, tp=1, ep=4)
        assert cand.batch_spec() == P(("dp", "fsdp", "ep"))
        assert cand.mesh_shape()["ep"] == 4
        assert cand.axis_names == ("dp", "fsdp", "tp", "ep")
        # dense candidates keep the canonical 3-axis mesh
        assert MeshCandidate(dp=8).axis_names == ("dp", "fsdp", "tp")

    def test_plan_scores_and_charges_dispatch_a2a(self):
        moe = self._moe()
        x = pp.randn([8, 16, 64])
        res = autoshard.plan(moe, x, n_devices=8)
        eps = [s for s in res.scored
               if s.candidate.ep > 1 and s.pruned is None]
        assert eps, "no ep candidate survived"
        # the dispatch/combine pair + backward twins are charged on every
        # ep candidate, at no more than the undiscounted ring time
        for s in eps:
            assert s.n_collectives >= 4, s.candidate.label
            assert s.collective_bytes > 0
            assert 0.0 < s.collective_s <= s.collective_raw_s + 1e-12
        # and the charge follows collective_seconds: pure-EP moves the
        # most tokens over the widest axis, so it pays more a2a than a
        # variant that splits the same devices with dp
        by_label = {s.candidate.label: s for s in eps}
        assert by_label["dp1xfsdp1xtp1xep8"].collective_bytes >= \
            by_label["dp4xfsdp1xtp1xep2"].collective_bytes

    def test_ep_plans_roundtrip_checker_clean(self):
        moe = self._moe()
        x = pp.randn([8, 16, 64])
        res = autoshard.plan(moe, x, n_devices=8, topk=10)
        ep_plans = [p for p in res.plans if p.candidate.ep > 1]
        assert ep_plans, "no ep plan in the top k"
        for p in ep_plans:
            rep = p.verify(moe, x)
            assert not rep.errors() and not rep.warnings(), (
                p.candidate.label + "\n" + rep.format())
            assert ("all_to_all", ("ep",)) in p.expected_collectives
            mesh = p.jax_mesh()
            assert dict(mesh.shape)["ep"] == p.candidate.ep
            sh = p.shardings()
            assert sh["experts.w1"].spec == p.param_specs["experts.w1"]
