"""Measurement ledger + calibrated cost model (ISSUE 17).

What must hold for a measurement corpus to be trustworthy enough that
the planner ranks by it and the fusion router routes by it:

* records written by one process are served to a FRESH process (same
  key discipline as the compile cache);
* backend fencing is absolute — a CPU-measured record can never answer
  a TPU query, and vice versa (the fingerprint carries device count
  too);
* a corrupt / truncated / old-schema ledger file — or a malformed
  entry inside a healthy file — is silently invalidated, never raised;
* residual math is exact (measured/predicted), coverage-gated: a query
  the ledger cannot serve falls back to the raw prediction unchanged;
* with the knob off there is ZERO behavior change: planner scores and
  fusion-tier routing are identical to the uncalibrated build;
* the ``calibration_drift`` watchdog rule fires on divergence in
  either direction, respects cooldown, and stays silent when
  calibration is off.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.observability import calibration
from paddle_tpu.observability.calibration import (CalibratedCostModel,
                                                  MeasurementLedger,
                                                  make_key, shape_bucket)
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.recorder import FlightRecorder
from paddle_tpu.observability.watchdog import (RULE_TYPES,
                                               CalibrationDriftRule,
                                               Watchdog, default_rules)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cal_env(tmp_path, monkeypatch):
    d = str(tmp_path / "calibration")
    monkeypatch.setenv("PADDLE_TPU_CALIBRATION", "1")
    monkeypatch.setenv("PADDLE_TPU_CALIBRATION_DIR", d)
    calibration.reset()
    yield d
    calibration.reset()


@pytest.fixture
def cal_off(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_CALIBRATION", raising=False)
    calibration.reset()
    yield
    calibration.reset()


# ----------------------------------------------------------------- keys
class TestKeys:
    def test_shape_bucket_pow2_rows(self):
        # leading dims flatten to a row count; everything rounds up
        assert shape_bucket((2, 16)) == "r2x16"
        assert shape_bucket((2, 16, 64)) == "r32x64"
        assert shape_bucket((4, 2048, 2048)) == "r8192x2048"
        assert shape_bucket((8, 1024, 2048)) == "r8192x2048"
        assert shape_bucket((5,)) == "r8"
        assert shape_bucket(()) == "scalar"

    def test_string_shape_passes_through(self):
        # autotune keys are already content-addressed
        assert shape_bucket("f32[128,256]") == "f32[128,256]"

    def test_make_key_format_and_backend(self):
        k = make_key("attention", (4, 64, 128), "float32",
                     backend="tpu:v5e:n8")
        assert k == "attention|r256x128|float32|-@tpu:v5e:n8"
        # default backend is THIS process's fingerprint
        assert make_key("x", (2, 2)).endswith(
            "@" + calibration.backend_tag())


# --------------------------------------------------------------- ledger
class TestLedger:
    def test_round_trip_fresh_instance(self, cal_env):
        led = MeasurementLedger()
        key = led.record("attention", (4, 64, 128), "float32",
                         measured_s=1.5e-3, predicted_s=1.0e-3,
                         provenance="device_profiler")
        assert key.startswith("attention|r256x128|float32|-@")
        # a FRESH instance (new process simulation) reads the file
        other = MeasurementLedger()
        e = other.query("attention", (4, 64, 128), "float32")
        assert e is not None
        assert e["measured_s"] == pytest.approx(1.5e-3)
        assert e["predicted_s"] == pytest.approx(1.0e-3)
        assert e["provenance"] == ["device_profiler"]

    def test_aggregation_min_mean_count_provenance(self, cal_env):
        led = MeasurementLedger()
        led.record("mm", (8, 8), measured_s=2.0e-3, provenance="bench")
        led.record("mm", (8, 8), measured_s=1.0e-3, predicted_s=5e-4,
                   provenance="autotune")
        e = led.query("mm", (8, 8))
        assert e["measured_s"] == pytest.approx(1.0e-3)   # running min
        assert e["mean_s"] == pytest.approx(1.5e-3)
        assert e["n"] == 2
        assert e["provenance"] == ["autotune", "bench"]
        assert e["predicted_s"] == pytest.approx(5e-4)    # latest nonzero

    def test_rejects_garbage_measurements(self, cal_env):
        led = MeasurementLedger()
        assert led.record("mm", (8, 8), measured_s=0.0) == ""
        assert led.record("mm", (8, 8), measured_s=-1.0) == ""
        assert led.record("mm", (8, 8), measured_s=float("nan")) == ""
        assert led.query("mm", (8, 8)) is None

    def test_backend_fencing(self, cal_env):
        """A CPU record can NEVER answer a TPU query (and vice versa)."""
        led = MeasurementLedger()
        led.record("attention", (4, 64, 128), "float32",
                   measured_s=1e-3)          # this (CPU) backend
        # same population, different chip: nothing served
        assert led.query("attention", (4, 64, 128), "float32",
                         backend="tpu:v5e:n8") is None
        # a TPU-tagged record is invisible to this CPU process's
        # default query
        led.record("matmul", (128, 128), "bfloat16", measured_s=2e-4,
                   backend="tpu:v5e:n8")
        assert led.query("matmul", (128, 128), "bfloat16") is None
        assert led.query("matmul", (128, 128), "bfloat16",
                         backend="tpu:v5e:n8") is not None
        # device count is fenced too (n8 != n16)
        assert led.query("matmul", (128, 128), "bfloat16",
                         backend="tpu:v5e:n16") is None

    def test_entries_backend_filter(self, cal_env):
        led = MeasurementLedger()
        led.record("a", (2, 2), measured_s=1e-3)
        led.record("b", (2, 2), measured_s=1e-3, backend="tpu:v5e:n8")
        mine = led.entries(backend=calibration.backend_tag())
        assert len(mine) == 1 and len(led.entries()) == 2

    def test_corrupt_file_silently_invalidated(self, cal_env):
        os.makedirs(cal_env, exist_ok=True)
        with open(calibration.ledger_path(), "w") as f:
            f.write("{ not json !!")
        led = MeasurementLedger()
        assert led.entries() == {}
        # and recording over the corpse works (atomic replace)
        led.record("mm", (8, 8), measured_s=1e-3)
        assert MeasurementLedger().query("mm", (8, 8)) is not None

    def test_truncated_file_silently_invalidated(self, cal_env):
        led = MeasurementLedger()
        led.record("mm", (8, 8), measured_s=1e-3)
        path = calibration.ledger_path()
        blob = open(path).read()
        with open(path, "w") as f:
            f.write(blob[:len(blob) // 2])
        assert MeasurementLedger().entries() == {}

    def test_old_schema_silently_invalidated(self, cal_env):
        os.makedirs(cal_env, exist_ok=True)
        entry = {"op_class": "mm", "measured_s": 1e-3, "mean_s": 1e-3,
                 "predicted_s": 0.0, "n": 1, "provenance": ["manual"],
                 "updated": 0.0}
        with open(calibration.ledger_path(), "w") as f:
            json.dump({"version": calibration.LEDGER_VERSION + 98,
                       "entries": {"mm|r8x8|-|-@x:y:n1": entry}}, f)
        assert MeasurementLedger().entries() == {}

    def test_malformed_entry_dropped_sibling_kept(self, cal_env):
        led = MeasurementLedger()
        good = led.record("mm", (8, 8), measured_s=1e-3)
        path = calibration.ledger_path()
        raw = json.load(open(path))
        raw["entries"]["bad|r2x2|-|-@x:y:n1"] = {"measured_s": -4.0}
        raw["entries"]["worse|r2x2|-|-@x:y:n1"] = "not a dict"
        with open(path, "w") as f:
            json.dump(raw, f)
        ents = MeasurementLedger().entries()
        assert list(ents) == [good]

    def test_concurrent_writers_merge_not_clobber(self, cal_env):
        """Two ledgers on the same path: the later save overlays the
        earlier one's keys instead of erasing them."""
        a, b = MeasurementLedger(), MeasurementLedger()
        a.record("seg_a", (8, 8), measured_s=1e-3)    # a saves first
        b.record("seg_b", (8, 8), measured_s=2e-3)    # b merges over
        ents = MeasurementLedger().entries()
        assert len(ents) == 2

    @pytest.mark.slow  # subprocess boot; the CI calibration gate runs it
    def test_round_trip_across_real_processes(self, cal_env):
        script = (
            "from paddle_tpu.observability import calibration\n"
            "e = calibration.ledger().query('attention', (4, 64, 128),"
            " 'float32')\n"
            "assert e is not None and abs(e['measured_s'] - 1.5e-3)"
            " < 1e-9, e\n"
            "print('SERVED')\n")
        MeasurementLedger().record("attention", (4, 64, 128), "float32",
                                   measured_s=1.5e-3, predicted_s=1e-3)
        env = dict(os.environ, PADDLE_TPU_CALIBRATION="1",
                   PADDLE_TPU_CALIBRATION_DIR=cal_env,
                   JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                             env=env, capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        assert "SERVED" in out.stdout


# ----------------------------------------------------- calibrated model
class TestCalibratedCostModel:
    def test_residual_math(self, cal_env):
        led = MeasurementLedger()
        led.record("attention", (4, 64, 128), "float32",
                   measured_s=2e-3, predicted_s=1e-3)
        model = CalibratedCostModel(led)
        assert model.residual_for("attention", (4, 64, 128),
                                  "float32") == pytest.approx(2.0)
        cal_s, res = model.calibrate(5e-4, "attention", (4, 64, 128),
                                     "float32")
        assert cal_s == pytest.approx(1e-3)
        assert res == pytest.approx(2.0)

    def test_coverage_gated_fallback(self, cal_env):
        led = MeasurementLedger()
        led.record("covered", (8, 8), measured_s=2e-3, predicted_s=1e-3)
        led.record("no_pred", (8, 8), measured_s=2e-3)  # no prediction
        model = CalibratedCostModel(led)
        # no entry at all -> raw prediction unchanged, residual None
        assert model.calibrate(7e-4, "missing", (8, 8)) == (7e-4, None)
        # entry without a prediction cannot produce a residual either
        assert model.calibrate(7e-4, "no_pred", (8, 8)) == (7e-4, None)
        assert model.calibrate(1e-3, "covered", (8, 8))[1] is not None
        assert model.coverage() == pytest.approx(1.0 / 3.0)

    def test_min_records_gate(self, cal_env):
        led = MeasurementLedger()
        led.record("mm", (8, 8), measured_s=2e-3, predicted_s=1e-3)
        assert CalibratedCostModel(led, min_records=2).residual_for(
            "mm", (8, 8)) is None
        led.record("mm", (8, 8), measured_s=2e-3, predicted_s=1e-3)
        assert CalibratedCostModel(led, min_records=2).residual_for(
            "mm", (8, 8)) == pytest.approx(2.0)

    def test_gauges_published(self, cal_env):
        reg = MetricsRegistry()
        led = MeasurementLedger()
        led.record("mm", (8, 8), measured_s=3e-3, predicted_s=1e-3)
        model = CalibratedCostModel(led, registry=reg)
        model.residual_for("mm", (8, 8))
        g = reg.get("paddle_tpu_calibration_residual")
        vals = {"/".join(k): c.value() for k, c in g.series()}
        assert vals["mm"] == pytest.approx(3.0)
        cov = reg.get("paddle_tpu_calibration_coverage")
        assert cov.value() == pytest.approx(1.0)

    def test_measured_for(self, cal_env):
        led = MeasurementLedger()
        led.record("decoder_block", (2, 16, 64), "float32",
                   layout="tier=off", measured_s=4e-3)
        model = CalibratedCostModel(led)
        assert model.measured_for("decoder_block", (2, 16, 64),
                                  "float32",
                                  layout="tier=off") == \
            pytest.approx(4e-3)
        assert model.measured_for("decoder_block", (2, 16, 64),
                                  "float32",
                                  layout="tier=fused") is None


# ------------------------------------------------------ overlap fraction
class TestOverlapFraction:
    def test_measured_overlap_served_when_enabled(self, cal_env):
        calibration.record_overlap_fraction(0.55, provenance="bench")
        assert calibration.calibrated_overlap_fraction(0.9) == \
            pytest.approx(0.55)

    def test_default_when_no_record(self, cal_env):
        assert calibration.calibrated_overlap_fraction(0.75) == 0.75

    def test_knob_off_returns_default(self, cal_off):
        assert calibration.calibrated_overlap_fraction(0.75) == 0.75


# --------------------------------------------------------------- planner
def _tiny_plan_inputs():
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pp.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    opt = pp.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = {"input_ids": jax.ShapeDtypeStruct((8, 16), jnp.int32),
             "labels": jax.ShapeDtypeStruct((8, 16), jnp.int32)}
    return step, batch


class TestPlannerCalibration:
    def test_knob_off_scores_are_raw(self, cal_off):
        from paddle_tpu.analysis import autoshard
        step, batch = _tiny_plan_inputs()
        res = autoshard.plan(step, batch, n_devices=8, topk=3)
        for sc in res.scored:
            assert sc.calibrated_s is None and sc.residual is None
            if sc.pruned is None:
                assert sc.step_seconds == sc.raw_step_seconds
        txt = res.table()
        assert "calib ms" not in txt and "resid" not in txt

    def test_calibrated_column_and_reference_exactness(self, cal_env):
        from paddle_tpu.analysis import autoshard
        step, batch = _tiny_plan_inputs()
        measured = 0.05
        MeasurementLedger().record("train_step", (8, 16),
                                   measured_s=measured,
                                   provenance="bench")
        res = autoshard.plan(step, batch, n_devices=8, topk=3)
        live = [s for s in res.scored if s.pruned is None]
        assert live and all(s.calibrated_s is not None for s in live)
        # the residual is anchored on the pure-DP reference candidate:
        # its calibrated time IS the measured time (within fp noise,
        # far inside the 15% acceptance bound)
        ref = next(s for s in live if s.candidate.fsdp == 1
                   and s.candidate.tp == 1
                   and getattr(s.candidate, "pp", 1) == 1)
        assert abs(ref.calibrated_s - measured) / measured < 0.15
        assert ref.calibrated_s == pytest.approx(measured)
        # every candidate scaled by the same factor: ranking by
        # step_seconds == ranking by raw_step_seconds
        raws = sorted(live, key=lambda s: s.raw_step_seconds)
        cals = sorted(live, key=lambda s: s.step_seconds)
        assert [s.candidate for s in raws] == [s.candidate for s in cals]
        txt = res.table()
        assert "calib ms" in txt and "resid" in txt
        assert "measurement-ledger residual" in txt

    def test_no_coverage_leaves_scores_raw(self, cal_env):
        # knob ON but empty ledger: coverage gate keeps everything raw
        from paddle_tpu.analysis import autoshard
        step, batch = _tiny_plan_inputs()
        res = autoshard.plan(step, batch, n_devices=8, topk=3)
        assert all(s.calibrated_s is None for s in res.scored)
        assert "calib ms" not in res.table()


# ------------------------------------------------------------ drift rule
class TestCalibrationDriftRule:
    def _wd(self, reg, factor=4.0, cooldown=60.0):
        return Watchdog(rules=[CalibrationDriftRule(factor=factor)],
                        registry=reg, recorder=FlightRecorder(),
                        cooldown=cooldown)

    def test_silent_without_metric(self):
        reg = MetricsRegistry()
        assert CalibrationDriftRule().evaluate(reg, now=0.0) is None

    def test_silent_when_healthy(self):
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_calibration_residual", "r",
                      labelnames=("segment",))
        g.labels(segment="mm").set(1.5)
        assert CalibrationDriftRule(factor=4.0).evaluate(
            reg, now=0.0) is None

    def test_fires_both_directions(self):
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_calibration_residual", "r",
                      labelnames=("segment",))
        g.labels(segment="mm").set(10.0)       # model optimistic 10x
        msg = CalibrationDriftRule(factor=4.0).evaluate(reg, now=0.0)
        assert msg and "10.00x" in msg and "mm" in msg
        g.labels(segment="mm").set(0.05)       # model pessimistic 20x
        assert CalibrationDriftRule(factor=4.0).evaluate(
            reg, now=0.0) is not None

    def test_fire_cooldown_refire_via_watchdog(self):
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_calibration_residual", "r",
                      labelnames=("segment",))
        g.labels(segment="train_step").set(10.0)
        wd = self._wd(reg, cooldown=60.0)
        alerts = wd.evaluate_once(now=1000.0)
        assert len(alerts) == 1
        assert alerts[0].rule == "calibration_drift"
        # still bad 10s later: cooldown suppresses the re-alert
        assert wd.evaluate_once(now=1010.0) == []
        # past the cooldown it re-fires
        assert len(wd.evaluate_once(now=1100.0)) == 1

    def test_registered_in_defaults_and_spec(self):
        assert "calibration_drift" in RULE_TYPES
        assert any(isinstance(r, CalibrationDriftRule)
                   for r in default_rules())


# ------------------------------------------------------ profiler feeder
class TestProfilerFeeder:
    def test_records_accessor(self):
        from paddle_tpu.observability import DeviceProfiler
        prof = DeviceProfiler()
        x = jnp.ones((64, 64), jnp.float32)
        prof.add_segment("mm", lambda a: a @ a, x)
        prof.profile(reps=1, warmup=0, parent_span="test.records")
        recs = prof.records()
        assert len(recs) == 1 and recs[0].name == "mm"
        assert prof.records("mm") == recs
        assert prof.records("nope") == []
        # and the module-level log mirrors compile_records()
        from paddle_tpu.observability import segment_records
        assert any(r.name == "mm" for r in segment_records())
        assert segment_records("mm")[-1].device_s > 0

    def test_profile_feeds_ledger(self, cal_env):
        from paddle_tpu.observability import DeviceProfiler
        prof = DeviceProfiler()
        x = jnp.ones((64, 64), jnp.float32)
        prof.add_segment("mm", lambda a: a @ a, x)
        prof.profile(reps=1, warmup=0, parent_span="test.feed")
        # the row landed with shape/dtype of the primary arg, the
        # active fusion tier as layout, and the roofline prediction
        ents = MeasurementLedger().entries()
        keys = [k for k in ents if k.startswith("mm|r64x64|float32|")]
        assert keys, list(ents)
        e = ents[keys[0]]
        assert e["provenance"] == ["device_profiler"]
        assert e["measured_s"] > 0 and e["predicted_s"] > 0
        assert "|tier=" in keys[0]

    def test_profile_does_not_feed_when_off(self, cal_off, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CALIBRATION_DIR",
                           str(tmp_path / "cal_off"))
        calibration.reset()
        from paddle_tpu.observability import DeviceProfiler
        prof = DeviceProfiler()
        x = jnp.ones((16, 16), jnp.float32)
        prof.add_segment("mm_off", lambda a: a @ a, x)
        prof.profile(reps=1, warmup=0, parent_span="test.nofeed")
        assert not os.path.exists(calibration.ledger_path())


# -------------------------------------------------- measured fusion tier
class TestMeasuredTier:
    def test_no_coverage_defaults_to_fused(self, cal_env):
        from paddle_tpu.ops.pallas.fused_block import measured_tier_for
        assert measured_tier_for((2, 16, 64), "float32") == "fused"

    def test_picks_fastest_measured_tier(self, cal_env):
        from paddle_tpu.ops.pallas.fused_block import measured_tier_for
        # the router consults the process-wide ledger, so feed that one
        led = calibration.ledger()
        led.record("decoder_block_fused", (2, 16, 64), "float32",
                   layout="tier=decoder", measured_s=1e-3)
        led.record("decoder_block", (2, 16, 64), "float32",
                   layout="tier=fused", measured_s=3e-3)
        led.record("decoder_block", (2, 16, 64), "float32",
                   layout="tier=off", measured_s=5e-3)
        assert measured_tier_for((2, 16, 64), "float32") == "decoder"
        # a different shape bucket is a different population
        assert measured_tier_for((2, 512, 64), "float32") == "fused"
        # flip the winner: unfused measured fastest
        led.record("decoder_block", (2, 16, 64), "float32",
                   layout="tier=off", measured_s=1e-5)
        assert measured_tier_for((2, 16, 64), "float32") == "off"

    def test_measured_env_value(self, monkeypatch):
        from paddle_tpu.ops.pallas import fused_block as FB
        monkeypatch.setenv("PADDLE_TPU_FUSED_BLOCK", "measured")
        assert FB.fused_block_tier() == "measured"
        assert FB.fused_block_enabled() is True
        # the megakernel is routed per shape, not globally
        assert FB.fused_decoder_enabled() is False


# --------------------------------------------------------- CLI + bench
class TestLintCalibration:
    def _seed(self, n=6):
        led = MeasurementLedger()
        for i in range(n):
            led.record(f"seg{i}", (2 ** i, 64), "float32",
                       measured_s=(i + 1) * 1e-3,
                       predicted_s=1e-3, provenance="device_profiler")

    def test_renders_table(self, cal_env, capsys):
        from paddle_tpu.analysis import lint
        self._seed()
        assert lint.main(["--calibration"]) == 0
        out = capsys.readouterr().out
        assert "segment / op-class" in out
        assert "coverage" in out
        assert sum(1 for ln in out.splitlines()
                   if ln.startswith("seg")) >= 5

    def test_max_residual_gate(self, cal_env, capsys):
        from paddle_tpu.analysis import lint
        self._seed()
        # worst residual is 6.0x (seg5): the CI gate trips below that
        assert lint.main(["--calibration", "--max-residual", "4"]) == 1
        assert "FAIL" in capsys.readouterr().err
        assert lint.main(["--calibration", "--max-residual", "10"]) == 0

    def test_empty_ledger_is_not_an_error(self, cal_env, capsys):
        from paddle_tpu.analysis import lint
        assert lint.main(["--calibration"]) == 0


class TestBenchDetail:
    def test_disabled_section(self, cal_off):
        assert calibration.bench_detail() == {"enabled": False}

    def test_enabled_section(self, cal_env):
        led = MeasurementLedger()
        led.record("train_step", (8, 16), measured_s=2e-3,
                   predicted_s=1e-3, provenance="bench")
        led.record("nopred", (8, 16), measured_s=2e-3)
        d = calibration.bench_detail(registry=MetricsRegistry())
        assert d["enabled"] and d["entries"] == 2
        assert d["with_prediction"] == 1
        assert d["coverage"] == pytest.approx(0.5)
        assert d["residuals"]["train_step"] == pytest.approx(2.0)
        assert d["max_residual_factor"] == pytest.approx(2.0)

    def test_compare_flags_coverage_and_residual_regressions(self):
        import bench
        prev = {"detail": {"calibration": {
            "enabled": True, "coverage": 0.8, "mean_abs_residual": 0.5}}}
        cur_bad_cov = {"detail": {"calibration": {
            "enabled": True, "coverage": 0.4, "mean_abs_residual": 0.5}}}
        regs = bench.compare_records(cur_bad_cov, prev, tolerance=0.05)
        assert any("coverage" in r for r in regs)
        cur_bad_res = {"detail": {"calibration": {
            "enabled": True, "coverage": 0.8, "mean_abs_residual": 2.0}}}
        regs = bench.compare_records(cur_bad_res, prev, tolerance=0.05)
        assert any("residual" in r for r in regs)
        # guarded clause: sections missing on either side -> silent
        assert bench.compare_records({"detail": {}}, prev,
                                     tolerance=0.05) == []
        ok = {"detail": {"calibration": {
            "enabled": True, "coverage": 0.85,
            "mean_abs_residual": 0.55}}}
        assert bench.compare_records(ok, prev, tolerance=0.05) == []
