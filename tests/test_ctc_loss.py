"""CTC loss vs the torch oracle + gradient finiteness (regression for
the log-space alpha recursion's unreachable-state NaN: log(0) states
poisoned the backward pass; reference nn/functional/loss.py ctc_loss
over warpctc)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.core.dispatch import unwrap


CASES = [(16, 2, 97, 4), (25, 3, 40, 10), (12, 4, 30, 6),
         (8, 2, 12, 3)]


@pytest.mark.parametrize("T,b,K,L", CASES)
def test_ctc_matches_torch(T, b, K, L):
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(T * 31 + L)
    raw = rng.normal(size=(T, b, K)).astype(np.float32)
    logp = jax.nn.log_softmax(jnp.asarray(raw), -1)
    labels = rng.integers(1, K - 1, (b, L)).astype(np.int32)
    il = np.full((b,), T, np.int32)
    ll = np.full((b,), L, np.int32)
    ours = float(unwrap(F.ctc_loss(
        logp, jnp.asarray(labels), jnp.asarray(il), jnp.asarray(ll),
        blank=0, reduction="mean")))
    want = float(torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.from_numpy(raw), -1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(il.astype(np.int64)),
        torch.from_numpy(ll.astype(np.int64)),
        blank=0, reduction="mean"))
    np.testing.assert_allclose(ours, want, rtol=1e-5, atol=1e-5)


def test_ctc_grad_finite():
    """The gradient must be finite even with unreachable lattice states
    (short labels, long T — most of the alpha band starts dead)."""
    rng = np.random.default_rng(7)
    T, b, K, L = 20, 3, 50, 2
    logp = jax.nn.log_softmax(
        jnp.asarray(rng.normal(size=(T, b, K)), jnp.float32), -1)
    labels = jnp.asarray(rng.integers(1, K - 1, (b, L)), jnp.int32)
    il = jnp.full((b,), T, jnp.int32)
    ll = jnp.full((b,), L, jnp.int32)

    g = jax.grad(lambda lp: unwrap(F.ctc_loss(
        lp, labels, il, ll, blank=0, reduction="mean")))(logp)
    assert bool(jnp.isfinite(g).all())
    assert float(jnp.abs(g).sum()) > 0


def test_ctc_repeated_labels():
    """Repeats force blank transitions (allow_skip=False rows)."""
    torch = pytest.importorskip("torch")
    T, b, K = 12, 1, 10
    raw = np.random.default_rng(3).normal(size=(T, b, K)).astype(np.float32)
    labels = np.array([[2, 2, 3, 3]], np.int32)
    il = np.array([T], np.int32)
    ll = np.array([4], np.int32)
    ours = float(unwrap(F.ctc_loss(
        jax.nn.log_softmax(jnp.asarray(raw), -1), jnp.asarray(labels),
        jnp.asarray(il), jnp.asarray(ll), blank=0, reduction="mean")))
    want = float(torch.nn.functional.ctc_loss(
        torch.log_softmax(torch.from_numpy(raw), -1),
        torch.from_numpy(labels.astype(np.int64)),
        torch.from_numpy(il.astype(np.int64)),
        torch.from_numpy(ll.astype(np.int64)),
        blank=0, reduction="mean"))
    np.testing.assert_allclose(ours, want, rtol=1e-5, atol=1e-5)
