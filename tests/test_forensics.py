"""Request forensics (ISSUE 20): scheduler decision provenance, the
per-request cause attribution (``explain``), tail aggregation, store
federation, the ``tail_regression`` watchdog rule, and the CLI.

Pure-function and LocalStore-federation tests run in tier-1; the
engine/router chaos drills that must name the injected cause as
dominant are ``@slow`` and run unfiltered in CI's request-forensics
gate."""

import json

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.observability import forensics
from paddle_tpu.observability.fleet import (FleetAggregator, LocalStore,
                                            MetricsPublisher)
from paddle_tpu.observability.forensics import (CAUSES, DECISION_KINDS,
                                                MAX_ALTERNATIVES,
                                                attribute,
                                                collect_decisions,
                                                decision_events,
                                                decisions_to_chrome,
                                                dominant_cause,
                                                emit_decision, explain,
                                                extract_decisions,
                                                inject_decisions,
                                                observe_retirement,
                                                summarize_attributions,
                                                tail_report)
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.recorder import flight_recorder
from paddle_tpu.observability.watchdog import (TailRegressionRule,
                                               rules_from_spec)
from paddle_tpu.robustness import clear_faults, inject


@pytest.fixture(autouse=True)
def _clean_ring_and_faults():
    flight_recorder().clear()
    clear_faults()
    yield
    flight_recorder().clear()
    clear_faults()


def _ev(kind, t, seq, **fields):
    """A hand-built recorder-event dict, as dumps/federation carry."""
    return {"kind": f"decision.{kind}", "time": t, "seq": seq, **fields}


def _retire(rid, t, seq, timings, status="completed", **fields):
    return _ev("retire", t, seq, rid=rid, chosen=status, status=status,
               source="router", timings=timings, **fields)


# ----------------------------------------------------------- timings canon
class TestTimingsSchema:
    def test_request_timings_always_complete(self):
        """Every TIMING_KEYS key is present on a freshly-enqueued
        request — phases never reached read 0.0, so attribution and
        bench folds need no feature detection (and no downstream
        setdefault patches)."""
        from paddle_tpu.inference.serving import (TIMING_KEYS, _Request,
                                                  _request_timings)
        req = _Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                       max_new_tokens=2)
        t = _request_timings(req)
        assert set(t) == set(TIMING_KEYS)
        assert t["queue_s"] == 0.0 and t["resume_s"] == 0.0
        assert t["route_s"] == 0.0 and t["handoff_s"] == 0.0

    def test_attribute_accepts_bare_schema(self):
        from paddle_tpu.inference.serving import (_Request,
                                                  _request_timings)
        req = _Request(rid=0, prompt=np.arange(4, dtype=np.int32),
                       max_new_tokens=2)
        causes = attribute(_request_timings(req))
        assert set(causes) == set(CAUSES)
        assert dominant_cause(causes) == "none"


# ------------------------------------------------------------------- emit
class TestEmit:
    def test_alternatives_bounded_with_overflow_count(self):
        alts = [{"replica": f"r{i}", "load": i} for i in range(12)]
        emit_decision("route", rid=1, chosen={"replica": "r0"},
                      alternatives=alts, policy="least_loaded")
        [dec] = decision_events()
        assert dec.kind == "route" and dec.rid == 1
        assert len(dec.alternatives) == MAX_ALTERNATIVES
        assert dec.fields["alternatives_dropped"] == 4
        assert dec.fields["policy"] == "least_loaded"

    def test_knob_off_is_a_noop(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FORENSICS", "0")
        emit_decision("route", rid=1, chosen="r0")
        assert decision_events() == []
        # the overage counter is not even created when off
        reg = MetricsRegistry()
        observe_retirement({"ttft_s": 3.0, "queue_s": 2.0},
                           targets={"ttft": 0.5, "tpot": 0.0},
                           registry=reg)
        assert reg.get("paddle_tpu_slo_overage_seconds_total") is None

    def test_every_kind_round_trips(self):
        for i, kind in enumerate(DECISION_KINDS):
            emit_decision(kind, rid=i, chosen="x")
        decs = decision_events()
        assert [d.kind for d in decs] == list(DECISION_KINDS)
        # rid filter is string-tolerant (JSON round-trips int rids)
        assert decision_events(rid="3")[0].kind == DECISION_KINDS[3]


# -------------------------------------------------------------- attribute
class TestAttribute:
    def test_route_share_is_route_minus_queue(self):
        causes = attribute({"queue_s": 2.0, "route_s": 2.5})
        assert causes["queue_wait"] == pytest.approx(2.0)
        assert causes["route"] == pytest.approx(0.5)
        assert dominant_cause(causes) == "queue_wait"

    def test_resume_path_heuristic(self):
        promote = attribute({"resume_s": 0.4, "handoff_s": 0.1})
        assert promote["cold_resume.promote"] == pytest.approx(0.4)
        recompute = attribute({"resume_s": 0.4})
        assert recompute["cold_resume.recompute"] == pytest.approx(0.4)
        assert dominant_cause(recompute) == "cold_resume.recompute"

    def test_resume_decision_event_wins_over_heuristic(self):
        evs = decision_events([_ev("resume", 1.0, 1, rid=0,
                                   chosen="recompute",
                                   path="recompute")])
        causes = attribute({"resume_s": 0.4, "handoff_s": 0.1}, evs)
        assert causes["cold_resume.recompute"] == pytest.approx(0.4)
        assert causes["cold_resume.promote"] == 0.0

    def test_requeue_folds_final_life_queue_and_route(self):
        """A retried request's final-life queue wait and router
        overhead exist only because of the requeue: they fold into the
        requeue cause instead of double-counting as queue/route."""
        evs = decision_events([_ev("requeue", 1.0, 1, rid=0,
                                   chosen="recompute",
                                   reason="replica_death",
                                   wasted_s=2.0)])
        causes = attribute({"queue_s": 1.0, "route_s": 3.5,
                            "attempts": 2.0}, evs)
        assert causes["requeue"] == pytest.approx(3.5)   # 1.0 + 2.5
        assert causes["queue_wait"] == 0.0
        assert causes["route"] == 0.0
        assert dominant_cause(causes) == "requeue"

    def test_requeue_wasted_can_exceed_route_window(self):
        evs = decision_events([_ev("requeue", 1.0, 1, rid=0,
                                   wasted_s=4.0)])
        causes = attribute({"queue_s": 1.0, "route_s": 3.5}, evs)
        assert causes["requeue"] == pytest.approx(5.0)   # 1.0 + 4.0

    def test_requeue_from_attempts_alone(self):
        # bench path: timings only, no events — attempts > 1 is enough
        causes = attribute({"queue_s": 0.5, "route_s": 2.0,
                            "attempts": 2.0})
        assert causes["requeue"] == pytest.approx(2.0)
        assert dominant_cause(causes) == "requeue"

    def test_all_productive_time_is_dominant_none(self):
        causes = attribute({"prefill_s": 1.0, "decode_s": 2.0})
        assert dominant_cause(causes) == "none"

    def test_summarize_shape_and_cold_share(self):
        rep = summarize_attributions([
            attribute({"queue_s": 3.0, "prefill_s": 1.0}),
            attribute({"resume_s": 1.0, "decode_s": 1.0}),
        ])
        assert rep["requests"] == 2
        assert rep["dominant_cause"] == "queue_wait"
        assert set(rep["causes"]) == set(CAUSES)
        assert rep["cold_resume_share"] == pytest.approx(
            rep["causes"]["cold_resume.recompute"]["share"])
        total_share = sum(v["share"] for v in rep["causes"].values())
        assert total_share == pytest.approx(1.0, abs=1e-4)


# ---------------------------------------------------------------- explain
_TIMINGS = {"queue_s": 2.0, "route_s": 2.5, "ttft_s": 3.0,
            "prefill_s": 0.4, "decode_s": 0.6, "total_s": 3.6,
            "generated": 4.0}


class TestExplain:
    def test_explain_joins_events_and_retire_timings(self):
        evs = [_ev("route", 1.0, 1, rid=7, chosen={"replica": "r0"},
                   alternatives=[{"replica": "r1", "load": 3}]),
               _ev("admit", 2.0, 2, rid=7, chosen="slot", slot=0),
               _retire(7, 3.0, 3, _TIMINGS)]
        exp = explain(7, events=evs, targets={"ttft": 0.5, "tpot": 0.0})
        assert exp is not None
        assert exp.status == "completed"
        assert exp.dominant_cause == "queue_wait"
        assert exp.overage["ttft"] == pytest.approx(2.5)
        table = exp.table()
        assert "dominant cause: queue_wait" in table
        assert "decisions:" in table and "route" in table

    def test_explain_unknown_rid_is_none(self):
        assert explain("nope", events=[]) is None

    def test_router_retire_beats_engine_local(self):
        engine = dict(_TIMINGS, queue_s=9.0)
        evs = [_ev("retire", 1.0, 1, rid=7, chosen="completed",
                   status="completed", source="engine", routed=True,
                   timings=engine),
               _retire(7, 2.0, 2, _TIMINGS)]
        exp = explain(7, events=evs, targets={"ttft": 0.0, "tpot": 0.0})
        assert exp.timings["queue_s"] == 2.0


# ------------------------------------------------------------ tail report
class TestTailReport:
    def test_window_skips_routed_engine_retires(self):
        evs = [
            # engine-local retire of a ROUTED request: must not count
            _ev("retire", 1.0, 1, rid=7, chosen="completed",
                status="completed", source="engine", routed=True,
                timings=dict(_TIMINGS, queue_s=99.0)),
            _retire(7, 2.0, 2, _TIMINGS),
            _retire(8, 3.0, 3, {"queue_s": 0.1, "prefill_s": 1.0,
                                "decode_s": 1.0, "total_s": 2.2,
                                "ttft_s": 1.2, "generated": 3.0}),
        ]
        rep = tail_report(10, events=evs,
                          targets={"ttft": 0.5, "tpot": 0.0})
        assert rep["window"] == 2 and rep["requests"] == 2
        assert rep["dominant_cause"] == "queue_wait"
        assert rep["overage_s"]["ttft"] == pytest.approx(2.5 + 0.7)
        assert rep["p99_total_s"] == pytest.approx(3.6)
        text = forensics.render_tail_report(rep)
        assert "dominant cause: queue_wait" in text

    def test_observe_retirement_feeds_overage_counter(self):
        reg = MetricsRegistry()
        over = observe_retirement(_TIMINGS,
                                  targets={"ttft": 0.5, "tpot": 0.1},
                                  registry=reg)
        assert over["ttft"] == pytest.approx(2.5)
        m = reg.get("paddle_tpu_slo_overage_seconds_total")
        by = {labels: child.value() for labels, child in m.series()}
        # TTFT overage split across overhead causes proportionally:
        # queue_wait 2.0 / route 0.5 of 2.5 overhead
        assert by[("ttft", "queue_wait")] == pytest.approx(2.0)
        assert by[("ttft", "route")] == pytest.approx(0.5)
        # TPOT overage lands on decode: 0.6/3 - 0.1 per token * 3
        assert by[("tpot", "decode")] == pytest.approx(0.3)

    def test_tail_regression_rule_names_dominant_cause(self):
        reg = MetricsRegistry()
        ctr = reg.counter("paddle_tpu_slo_overage_seconds_total",
                          labelnames=("kind", "cause"))
        rule = TailRegressionRule(min_overage_s=0.1, growth=2.0)
        assert rule.evaluate(reg, 0.0) is None          # snapshot
        ctr.labels(kind="ttft", cause="route").inc(0.05)
        assert rule.evaluate(reg, 1.0) is None          # baseline
        ctr.labels(kind="ttft", cause="queue_wait").inc(1.0)
        ctr.labels(kind="ttft", cause="route").inc(0.1)
        detail = rule.evaluate(reg, 2.0)
        assert detail is not None
        assert "dominant cause: queue_wait" in detail
        assert "flipped from route" in detail

    def test_rule_registered_in_spec_parser(self):
        [rule] = rules_from_spec("tail_regression:min_overage_s=0.2")
        assert isinstance(rule, TailRegressionRule)
        assert rule.min_overage_s == pytest.approx(0.2)


# ------------------------------------------------- federation (two hosts)
class TestFederation:
    def test_two_hosts_merge_and_aggregator_side_explain(self):
        """Satellite: two synthetic hosts publish decision windows over
        one LocalStore; the aggregator-side explain() joins a request
        whose route decision and retirement live on DIFFERENT hosts."""
        store = LocalStore()
        h0 = [_ev("route", 1.0, 1, rid=7, chosen={"replica": "r1"},
                  alternatives=[{"replica": "r0", "load": 5}])]
        h1 = [_ev("admit", 1.5, 1, rid=7, chosen="slot", slot=0),
              _retire(7, 2.0, 2, _TIMINGS)]
        assert inject_decisions(store, "obs/forensics/h0", host="h0",
                                events=h0) == 1
        assert inject_decisions(store, "obs/forensics/h1", host="h1",
                                events=h1) == 2
        store.set("obs/hosts", b"h0,h1")
        merged = collect_decisions(store)
        assert [e["host"] for e in merged] == ["h0", "h1", "h1"]
        exp = explain(7, events=merged,
                      targets={"ttft": 0.5, "tpot": 0.0})
        assert exp.dominant_cause == "queue_wait"
        assert {d.host for d in exp.events} == {"h0", "h1"}

    def test_publisher_to_aggregator_roundtrip(self):
        emit_decision("route", rid=3, chosen={"replica": "r0"})
        emit_decision("retire", rid=3, chosen="completed",
                      status="completed", source="router",
                      timings=_TIMINGS)
        store = LocalStore()
        pub = MetricsPublisher(store, registry=MetricsRegistry(),
                               host="solo", interval=999,
                               publish_goodput=False)
        pub.publish_once()
        agg = FleetAggregator(store=store)
        assert agg.poll() == ["solo"]
        evs = agg.decision_events()
        assert len(evs) == 2 and all(e["host"] == "solo" for e in evs)
        exp = agg.explain(3)
        assert exp is not None and exp.dominant_cause == "queue_wait"

    def test_publish_decisions_knob_off_writes_nothing(self):
        emit_decision("route", rid=3, chosen="r0")
        store = LocalStore()
        pub = MetricsPublisher(store, registry=MetricsRegistry(),
                               host="solo", interval=999,
                               publish_goodput=False,
                               publish_decisions=False)
        pub.publish_once()
        assert not [k for k in store._kv if "forensics" in k]

    def test_extract_is_tolerant(self):
        store = LocalStore()
        assert extract_decisions(store, "obs/forensics/gone") is None
        store.set("bad", b"not json at all")
        assert extract_decisions(store, "bad") is None
        store.set("old", json.dumps({"schema": 99,
                                     "events": []}).encode())
        assert extract_decisions(store, "old") is None
        store.set("mangled", json.dumps({"schema": 1,
                                         "events": "?"}).encode())
        assert extract_decisions(store, "mangled") is None


# ---------------------------------------------------------------- perfetto
class TestChromeExport:
    def test_instants_and_flow_chain_per_rid(self):
        evs = [_ev("route", 1.0, 1, rid=5, chosen={"replica": "r0"}),
               _ev("handoff", 2.0, 2, rid=5, chosen="ok"),
               _retire(5, 3.0, 3, _TIMINGS)]
        out = decisions_to_chrome(evs, pid=2)
        inst = [e for e in out if e["ph"] == "i"]
        assert len(inst) == 3
        assert all(e["cat"] == "forensics" and e["pid"] == 2
                   for e in inst)
        # retire timings stay out of args (they are bulky and live in
        # the tail report, not the timeline)
        assert all("timings" not in e["args"] for e in inst)
        flow = [e for e in out if e["ph"] in ("s", "t", "f")]
        assert [e["ph"] for e in flow] == ["s", "t", "f"]
        assert {e["id"] for e in flow} == {"forensics-5"}
        assert flow[-1]["bp"] == "e"

    def test_aggregator_export_includes_decisions(self, tmp_path):
        emit_decision("route", rid=3, chosen={"replica": "r0"})
        emit_decision("retire", rid=3, chosen="completed",
                      status="completed", source="router",
                      timings=_TIMINGS)
        store = LocalStore()
        MetricsPublisher(store, registry=MetricsRegistry(), host="solo",
                         interval=999,
                         publish_goodput=False).publish_once()
        agg = FleetAggregator(store=store)
        agg.poll()
        doc = agg.export_chrome(str(tmp_path / "trace.json"))
        names = [e.get("name") for e in doc["traceEvents"]]
        assert "decision.route" in names and "decision.retire" in names


# --------------------------------------------------------------------- CLI
class TestCli:
    def _events_file(self, tmp_path):
        evs = [_ev("route", 1.0, 1, rid=7, chosen={"replica": "r0"}),
               _retire(7, 2.0, 2, _TIMINGS)]
        path = tmp_path / "events.json"
        path.write_text(json.dumps(evs))
        return str(path)

    def test_explain_renders_dominant_cause(self, tmp_path, capsys):
        rc = forensics.main(["--events", self._events_file(tmp_path),
                             "--explain", "7"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "dominant cause: queue_wait" in out
        assert "decisions:" in out

    def test_tail_renders_report(self, tmp_path, capsys):
        rc = forensics.main(["--events", self._events_file(tmp_path),
                             "--tail", "5"])
        out = capsys.readouterr().out
        assert rc == 0 and "tail report over 1 retirements" in out

    def test_unknown_rid_exits_2(self, tmp_path, capsys):
        path = tmp_path / "empty.json"
        path.write_text("[]")
        rc = forensics.main(["--events", str(path), "--explain", "9"])
        assert rc == 2

    def test_reads_flight_recorder_dump(self, tmp_path, capsys):
        """The CI drill path: the engine dumps its ring as JSONL (with
        a header line) and the CLI explains straight from the file."""
        emit_decision("admit", rid=4, chosen="slot", slot=1)
        emit_decision("retire", rid=4, chosen="completed",
                      status="completed", source="router",
                      timings=_TIMINGS)
        dump = tmp_path / "ring.jsonl"
        flight_recorder().dump(file=str(dump), reason="forensics-test")
        rc = forensics.main(["--events", str(dump), "--explain", "4"])
        out = capsys.readouterr().out
        assert rc == 0 and "dominant cause: queue_wait" in out


# ------------------------------------------------------- bench comparison
class TestBenchCompare:
    @staticmethod
    def _record(dom, cold):
        return {"value": 100.0,
                "detail": {"tail_attribution": {
                    "requests": 4, "dominant_cause": dom,
                    "cold_resume_share": cold, "causes": {}}}}

    def test_dominant_cause_flip_is_a_regression(self):
        import bench
        prev = self._record("queue_wait", 0.0)
        assert bench.compare_serve_records(
            self._record("queue_wait", 0.0), prev) == []
        regs = bench.compare_serve_records(
            self._record("requeue", 0.0), prev)
        assert any("dominant_cause flipped" in r for r in regs)
        # flipping TO "none" (overhead vanished) is an improvement
        assert bench.compare_serve_records(
            self._record("none", 0.0), prev) == []

    def test_cold_resume_share_growth_is_a_regression(self):
        import bench
        prev = self._record("queue_wait", 0.1)
        regs = bench.compare_serve_records(
            self._record("queue_wait", 0.5), prev, tolerance=0.25)
        assert any("cold_resume_share" in r for r in regs)
        assert bench.compare_serve_records(
            self._record("queue_wait", 0.3), prev, tolerance=0.25) == []

    def test_guarded_when_either_side_lacks_the_section(self):
        import bench
        prev = self._record("queue_wait", 0.0)
        cur = {"value": 100.0, "detail": {}}
        assert not any("tail_attribution" in r for r in
                       bench.compare_serve_records(cur, prev))


# ---------------------------------------------------------------------
# engine / router chaos drills (real prefill; slow — the CI forensics
# gate runs them unfiltered): each injected failure must surface as the
# MATCHING dominant cause in explain()
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pp.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


ENGINE_KW = dict(slots=2, max_len=64, prefill_buckets=(32,),
                 paged_kv=True, kv_block_size=8, prefill_chunk=16)


def _build(model, tier=None, **over):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    kw = {**ENGINE_KW, **over}
    return ContinuousBatchingEngine(model, kv_tier=tier, **kw)


def _step_until_out(eng, rid, n):
    for _ in range(400):
        eng.step()
        slot = next((i for i, r in enumerate(eng._active)
                     if r is not None and r.rid == rid), None)
        if slot is not None and slot not in eng._prefilling \
                and len(eng._active[slot].out) >= n:
            return
    raise AssertionError("request never reached decode")


class _SpyStore(LocalStore):
    def __init__(self):
        super().__init__()
        self.set_keys = []

    def set(self, key, value):
        self.set_keys.append(key)
        return super().set(key, value)


@pytest.mark.slow
class TestForensicsDrills:
    def test_kv_alloc_exhaustion_names_queue_wait(self, tiny_model):
        import time
        from paddle_tpu.inference.kv_tier import KVTierManager
        eng = _build(tiny_model, tier=KVTierManager())
        rid = eng.add_request(np.arange(1, 17, dtype=np.int32),
                              max_new_tokens=4)
        inject("serving.kv_alloc", times=3)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.35:
            eng.step()
        clear_faults()
        eng.run()
        exp = forensics.explain(rid, status=eng.request_status(rid))
        assert exp.dominant_cause == "queue_wait", exp.causes
        deferred = decision_events(rid=rid, kind="admit")
        assert any(d.chosen == "defer" and
                   d.fields.get("reason") == "kv_alloc_exhausted"
                   for d in deferred)
        eng.close()

    def test_tier_fetch_miss_names_cold_resume_recompute(self,
                                                         tiny_model):
        from paddle_tpu.inference.kv_tier import KVTierManager
        eng = _build(tiny_model,
                     tier=KVTierManager(store=LocalStore()))
        rid = eng.add_request(np.arange(1, 17, dtype=np.int32),
                              max_new_tokens=8)
        _step_until_out(eng, rid, 3)
        assert eng.park(rid) is not False
        inject("kv_tier.fetch", times=1)
        assert eng.resume(rid) is not False
        clear_faults()
        eng.run()
        exp = forensics.explain(rid, status=eng.request_status(rid))
        assert exp.dominant_cause == "cold_resume.recompute", exp.causes
        paths = [d.fields.get("path")
                 for d in decision_events(rid=rid, kind="resume")]
        assert "recompute" in paths
        eng.close()

    def test_replica_death_names_requeue(self, tiny_model):
        from paddle_tpu.inference.kv_tier import KVTierManager
        from paddle_tpu.inference.router import ServingRouter
        prompts = [np.arange(1 + i, 17 + i, dtype=np.int32)
                   for i in range(3)]
        rt = ServingRouter(tiny_model, replicas=2,
                           engine_kwargs=dict(ENGINE_KW),
                           kv_tier=KVTierManager(store=LocalStore()),
                           session_checkpoint_steps=1)
        rids = [rt.add_request(p, max_new_tokens=8) for p in prompts]
        victim = None
        for _ in range(500):
            rt.step()
            for rep in rt._replicas.values():
                if rep.dead:
                    continue
                if any(r is not None and i not in rep.engine._prefilling
                       and len(r.out) >= 2
                       for i, r in enumerate(rep.engine._active)):
                    victim = rep.id
                    break
            if victim is not None:
                break
        assert victim is not None, "no replica reached decode"
        rt.kill_replica(victim)
        rt.run()
        doms = {rid: forensics.explain(
            rid, status=rt.request_status(rid)).dominant_cause
            for rid in rids}
        assert "requeue" in doms.values(), doms
        # death recovery emits a requeue decision either way: a
        # migrated session says so, a recomputed one blames the death
        reasons = {d.fields.get("reason")
                   for d in decision_events(kind="requeue")}
        assert reasons & {"replica_death", "session_migrate"}, reasons

    def test_observation_only_token_identity_and_zero_wire(
            self, tiny_model, monkeypatch):
        """Forensics on vs. off decodes identical tokens, and with no
        publisher attached nothing forensics-shaped touches the store
        — emission is ring-only."""
        from paddle_tpu.inference.kv_tier import KVTierManager
        prompts = [np.arange(1 + i, 13 + i, dtype=np.int32)
                   for i in range(2)]

        def run_once():
            spy = _SpyStore()
            eng = _build(tiny_model, tier=KVTierManager(store=spy))
            rids = [eng.add_request(p, max_new_tokens=6)
                    for p in prompts]
            res = eng.run()
            outs = [res[r][1] for r in rids]
            eng.close()
            return outs, spy

        pp.seed(0)
        outs_on, spy_on = run_once()
        assert len(decision_events(kind="retire")) >= 2
        assert not [k for k in spy_on.set_keys if "forensics" in k]

        flight_recorder().clear()
        monkeypatch.setenv("PADDLE_TPU_FORENSICS", "0")
        pp.seed(0)
        outs_off, _ = run_once()
        assert decision_events() == []       # knob-off: ring untouched
        assert outs_on == outs_off           # tokens untouched either way
