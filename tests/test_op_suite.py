"""Op-surface numeric suite on the OpTest harness (paddle_tpu.testing).

Mirrors the reference's test/legacy_test per-op OpTest files: every spec
declares numpy inputs + a numpy oracle; the harness checks eager / raw /
jit outputs and analytic-vs-finite-difference gradients.
"""

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.testing import op_case, binary_cases, unary_cases, _rand


def _sp(name, cases, grad_on_first=True):
    """(name, case, check_grad) triples for parametrization."""
    out = []
    for i, c in enumerate(cases):
        out.append(pytest.param(c, grad_on_first and i == 0,
                                id=f"{name}-{i}"))
    return out


# ----------------------------------------------------------- elementwise
BINARY = []
BINARY += _sp("add", binary_cases(pp.add, np.add))
BINARY += _sp("subtract", binary_cases(pp.subtract, np.subtract))
BINARY += _sp("multiply", binary_cases(pp.multiply, np.multiply))
BINARY += _sp("divide", binary_cases(pp.divide, np.divide, lo=0.5, hi=2.0))
BINARY += _sp("maximum", binary_cases(pp.maximum, np.maximum, grad=False))
BINARY += _sp("minimum", binary_cases(pp.minimum, np.minimum, grad=False))
BINARY += _sp("pow", binary_cases(pp.pow, np.power, lo=0.5, hi=2.0,
                                  grad_rtol=3e-2))
BINARY += _sp("atan2", binary_cases(pp.atan2, np.arctan2, lo=0.3, hi=2.0))
BINARY += _sp("hypot", binary_cases(pp.hypot, np.hypot, lo=0.5, hi=2.0))
BINARY += _sp("logaddexp", binary_cases(pp.logaddexp, np.logaddexp))
BINARY += _sp("heaviside", binary_cases(pp.heaviside, np.heaviside,
                                        grad=False))
BINARY += _sp("fmax", binary_cases(pp.fmax, np.fmax, grad=False))
BINARY += _sp("fmin", binary_cases(pp.fmin, np.fmin, grad=False))
BINARY += _sp("remainder", binary_cases(pp.remainder, np.remainder,
                                        lo=0.5, hi=2.0, grad=False))
BINARY += _sp("floor_divide", binary_cases(pp.floor_divide,
                                           np.floor_divide, lo=0.5, hi=3.0,
                                           grad=False))
BINARY += _sp("copysign", binary_cases(pp.copysign, np.copysign,
                                       grad=False))
BINARY += _sp("nextafter", binary_cases(pp.nextafter, np.nextafter,
                                        grad=False))

UNARY = []
UNARY += _sp("exp", unary_cases(pp.exp, np.exp))
UNARY += _sp("expm1", unary_cases(pp.expm1, np.expm1))
UNARY += _sp("log", unary_cases(pp.log, np.log, lo=0.5, hi=3.0))
UNARY += _sp("log2", unary_cases(pp.log2, np.log2, lo=0.5, hi=3.0))
UNARY += _sp("log10", unary_cases(pp.log10, np.log10, lo=0.5, hi=3.0))
UNARY += _sp("log1p", unary_cases(pp.log1p, np.log1p, lo=-0.4, hi=2.0))
UNARY += _sp("sqrt", unary_cases(pp.sqrt, np.sqrt, lo=0.3, hi=3.0))
UNARY += _sp("rsqrt", unary_cases(pp.rsqrt, lambda x: 1 / np.sqrt(x),
                                  lo=0.3, hi=3.0))
UNARY += _sp("abs", unary_cases(pp.abs, np.abs, lo=0.2, hi=2.0))
UNARY += _sp("ceil", unary_cases(pp.ceil, np.ceil, grad=False))
UNARY += _sp("floor", unary_cases(pp.floor, np.floor, grad=False))
UNARY += _sp("round", unary_cases(pp.round, np.round, grad=False))
UNARY += _sp("trunc", unary_cases(pp.trunc, np.trunc, grad=False))
UNARY += _sp("sign", unary_cases(pp.sign, np.sign, grad=False))
UNARY += _sp("sin", unary_cases(pp.sin, np.sin))
UNARY += _sp("cos", unary_cases(pp.cos, np.cos))
UNARY += _sp("tan", unary_cases(pp.tan, np.tan, lo=-1.0, hi=1.0))
UNARY += _sp("asin", unary_cases(pp.asin, np.arcsin, lo=-0.8, hi=0.8,
                                 grad_rtol=3e-2))
UNARY += _sp("acos", unary_cases(pp.acos, np.arccos, lo=-0.8, hi=0.8,
                                 grad_rtol=3e-2))
UNARY += _sp("atan", unary_cases(pp.atan, np.arctan))
UNARY += _sp("sinh", unary_cases(pp.sinh, np.sinh))
UNARY += _sp("cosh", unary_cases(pp.cosh, np.cosh))
UNARY += _sp("tanh", unary_cases(pp.tanh, np.tanh))
UNARY += _sp("asinh", unary_cases(pp.asinh, np.arcsinh))
UNARY += _sp("acosh", unary_cases(pp.acosh, np.arccosh, lo=1.3, hi=3.0))
UNARY += _sp("atanh", unary_cases(pp.atanh, np.arctanh, lo=-0.7, hi=0.7,
                                  grad_rtol=3e-2))
UNARY += _sp("reciprocal", unary_cases(pp.reciprocal, lambda x: 1.0 / x,
                                       lo=0.5, hi=2.0))
UNARY += _sp("square", unary_cases(pp.square, np.square))
UNARY += _sp("sigmoid", unary_cases(
    pp.nn.functional.sigmoid, lambda x: 1 / (1 + np.exp(-x))))
import scipy.special as _ss  # noqa: E402
UNARY += _sp("erf", unary_cases(pp.erf, _ss.erf))
UNARY += _sp("lgamma", unary_cases(pp.lgamma, _ss.gammaln, lo=0.5, hi=3.0,
                                   grad_rtol=3e-2))
UNARY += _sp("digamma", unary_cases(pp.digamma, _ss.digamma, lo=0.8,
                                    hi=3.0, grad_rtol=3e-2))
UNARY += _sp("expit-bf16", unary_cases(
    pp.exp, np.exp, dtypes=(np.float32,), grad=False))


# --------------------------------------------------------- reductions
def _reduction_cases():
    x = _rand((3, 4, 5))
    specs = [
        ("sum", op_case(pp.sum, lambda x, axis=None, keepdim=False:
                        np.sum(x, axis=axis, keepdims=keepdim),
                        {"x": x}, attrs={"axis": 1})),
        ("sum_keep", op_case(pp.sum, lambda x, axis=None, keepdim=False:
                             np.sum(x, axis=axis, keepdims=keepdim),
                             {"x": x}, attrs={"axis": (0, 2),
                                              "keepdim": True})),
        ("mean", op_case(pp.mean, lambda x, axis=None, keepdim=False:
                         np.mean(x, axis=axis, keepdims=keepdim),
                         {"x": x}, attrs={"axis": -1})),
        ("max", op_case(pp.max, lambda x, axis=None, keepdim=False:
                        np.max(x, axis=axis, keepdims=keepdim),
                        {"x": x}, attrs={"axis": 0}, grad_inputs=[])),
        ("min", op_case(pp.min, lambda x, axis=None, keepdim=False:
                        np.min(x, axis=axis, keepdims=keepdim),
                        {"x": x}, attrs={"axis": 2}, grad_inputs=[])),
        ("prod", op_case(pp.prod, lambda x, axis=None, keepdim=False,
                         dtype=None: np.prod(x, axis=axis, keepdims=keepdim),
                         {"x": _rand((3, 4), lo=0.5, hi=1.5)},
                         attrs={"axis": 1})),
        ("logsumexp", op_case(
            pp.logsumexp, lambda x, axis=None, keepdim=False:
            _ss.logsumexp(x, axis=axis, keepdims=keepdim),
            {"x": x}, attrs={"axis": 1})),
        ("cumsum", op_case(pp.cumsum, lambda x, axis=None, dtype=None:
                           np.cumsum(x, axis=axis), {"x": x},
                           attrs={"axis": 1})),
        ("cumprod", op_case(pp.cumprod, lambda x, dim=None, dtype=None:
                            np.cumprod(x, axis=dim),
                            {"x": _rand((3, 4), lo=0.5, hi=1.5)},
                            attrs={"dim": 1})),
        ("nansum", op_case(pp.nansum, lambda x, axis=None, dtype=None,
                           keepdim=False: np.nansum(x, axis=axis,
                                                    keepdims=keepdim),
                           {"x": x}, attrs={"axis": 0}, grad_inputs=[])),
        ("count_nonzero", op_case(
            pp.count_nonzero, lambda x, axis=None, keepdim=False:
            np.count_nonzero(x, axis=axis), {"x": x}, attrs={"axis": 1},
            grad_inputs=[])),
        ("trace", op_case(pp.trace, lambda x, offset=0, axis1=0, axis2=1:
                          np.trace(x, offset=offset, axis1=axis1,
                                   axis2=axis2),
                          {"x": _rand((4, 4))}, attrs={"offset": 1})),
    ]
    return [pytest.param(c, True, id=n) for n, c in specs]


# --------------------------------------------------------- manipulation
def _manip_cases():
    x = _rand((3, 4, 5))
    specs = [
        ("reshape", op_case(pp.reshape, lambda x, shape: np.reshape(x, shape),
                            {"x": x}, attrs={"shape": [4, 15]})),
        ("transpose", op_case(pp.transpose,
                              lambda x, perm: np.transpose(x, perm),
                              {"x": x}, attrs={"perm": [2, 0, 1]})),
        ("squeeze", op_case(pp.squeeze, lambda x, axis=None:
                            np.squeeze(x, axis=axis),
                            {"x": _rand((3, 1, 5))}, attrs={"axis": 1})),
        ("unsqueeze", op_case(pp.unsqueeze, lambda x, axis:
                              np.expand_dims(x, axis),
                              {"x": _rand((3, 4))}, attrs={"axis": 1})),
        ("flip", op_case(pp.flip, lambda x, axis: np.flip(x, axis),
                         {"x": x}, attrs={"axis": [0, 2]})),
        ("roll", op_case(pp.roll, lambda x, shifts, axis=None:
                         np.roll(x, shifts, axis),
                         {"x": x}, attrs={"shifts": 2, "axis": 1})),
        ("tile", op_case(pp.tile, lambda x, repeat_times:
                         np.tile(x, repeat_times),
                         {"x": _rand((2, 3))},
                         attrs={"repeat_times": [2, 2]})),
        ("gather", op_case(
            pp.gather, lambda x, index, axis=0: np.take(x, index, axis),
            {"x": x, "index": np.array([2, 0, 1])}, attrs={"axis": 1},
            grad_inputs=["x"])),
        ("index_select", op_case(
            pp.index_select,
            lambda x, index, axis=0: np.take(x, index, axis),
            {"x": x, "index": np.array([0, 2])}, attrs={"axis": 0},
            grad_inputs=["x"])),
        ("pad", op_case(
            pp.ops.manipulation.pad,
            lambda x, pad, mode="constant", value=0.0, **kw:
            np.pad(x, [(0, 0), (1, 2)], constant_values=value),
            {"x": _rand((3, 4))}, attrs={"pad": [1, 2], "value": 0.0})),
        ("where", op_case(
            pp.where, lambda c, x, y: np.where(c, x, y),
            {"condition": np.array([[True, False], [False, True]]),
             "x": _rand((2, 2)), "y": _rand((2, 2))},
            grad_inputs=["x", "y"])),
        ("masked_fill", op_case(
            pp.masked_fill,
            lambda x, m, value=0.0: np.where(m, np.float32(value), x),
            {"x": _rand((3, 4)), "mask": np.tri(3, 4) > 0},
            attrs={"value": 2.5}, grad_inputs=["x"])),
        ("take_along_axis", op_case(
            pp.take_along_axis,
            lambda a, idx, axis, broadcast=True:
            np.take_along_axis(a, idx, axis),
            {"arr": x, "indices": np.argsort(x, axis=2)}, attrs={"axis": 2},
            grad_inputs=["arr"])),
    ]
    return [pytest.param(c, True, id=n) for n, c in specs]


# --------------------------------------------------------- linalg / logic
def _linalg_cases():
    specs = [
        ("matmul", op_case(pp.matmul,
                           lambda x, y, transpose_x=False, transpose_y=False:
                           np.matmul(x, y),
                           {"x": _rand((3, 4)), "y": _rand((4, 5))},
                           rtol=2e-2, atol=2e-2, grad_rtol=3e-2)),
        ("matmul_bat", op_case(
            pp.matmul, lambda x, y, transpose_x=False, transpose_y=False:
            np.matmul(x, y),
            {"x": _rand((2, 3, 4)), "y": _rand((2, 4, 2))},
            rtol=2e-2, atol=2e-2, grad_rtol=3e-2)),
        ("matmul_tx", op_case(
            pp.matmul, lambda x, y, transpose_x=False, transpose_y=False:
            np.matmul(x.T, y),
            {"x": _rand((4, 3)), "y": _rand((4, 5))},
            attrs={"transpose_x": True}, rtol=2e-2, atol=2e-2,
            grad_rtol=3e-2)),
        ("dot", op_case(pp.dot, lambda x, y: np.sum(x * y, -1),
                        {"x": _rand((5,)), "y": _rand((5,))})),
        ("norm2", op_case(
            pp.ops.linalg.norm,
            lambda x, p=2, axis=None, keepdim=False:
            np.linalg.norm(x, axis=axis),
            {"x": _rand((3, 4), lo=0.3, hi=2.0)}, attrs={"axis": 1})),
    ]
    return [pytest.param(c, True, id=n) for n, c in specs]


def _logic_cases():
    a, b = _rand((3, 4)), _rand((3, 4))
    ints = np.arange(12).reshape(3, 4)
    specs = [
        ("equal", op_case(pp.equal, np.equal, {"x": ints, "y": ints.copy()},
                          grad_inputs=[])),
        ("not_equal", op_case(pp.not_equal, np.not_equal,
                              {"x": ints, "y": ints.T.reshape(3, 4)},
                              grad_inputs=[])),
        ("less_than", op_case(pp.less_than, np.less, {"x": a, "y": b},
                              grad_inputs=[])),
        ("greater_equal", op_case(pp.greater_equal, np.greater_equal,
                                  {"x": a, "y": b}, grad_inputs=[])),
        ("logical_and", op_case(pp.logical_and, np.logical_and,
                                {"x": a > 0, "y": b > 0}, grad_inputs=[])),
        ("logical_not", op_case(pp.logical_not, np.logical_not,
                                {"x": a > 0}, grad_inputs=[])),
        ("isnan", op_case(pp.isnan, np.isnan,
                          {"x": np.array([1.0, np.nan, np.inf])},
                          grad_inputs=[])),
        ("isfinite", op_case(pp.isfinite, np.isfinite,
                             {"x": np.array([1.0, np.nan, np.inf])},
                             grad_inputs=[])),
        ("argmax", op_case(pp.argmax, lambda x, axis=None:
                           np.argmax(x, axis=axis),
                           {"x": a}, attrs={"axis": 1}, grad_inputs=[])),
        ("argmin", op_case(pp.argmin, lambda x, axis=None:
                           np.argmin(x, axis=axis),
                           {"x": a}, attrs={"axis": 0}, grad_inputs=[])),
        ("argsort", op_case(pp.argsort, lambda x, axis=-1, descending=False:
                            np.argsort(x, axis=axis, kind="stable"),
                            {"x": a}, attrs={"axis": 1}, grad_inputs=[])),
        ("sort", op_case(pp.sort, lambda x, axis=-1, descending=False:
                         np.sort(x, axis=axis),
                         {"x": a}, attrs={"axis": 1}, grad_inputs=[])),
    ]
    return [pytest.param(c, True, id=n) for n, c in specs]


ALL_CASES = (BINARY + UNARY + _reduction_cases() + _manip_cases()
             + _linalg_cases() + _logic_cases())


@pytest.mark.parametrize("case,check_grad", ALL_CASES)
def test_op(case, check_grad):
    case.check_output()
    if check_grad:
        case.check_grad()
