"""Persistent AOT compile cache + model-artifact bundles (ROADMAP 5).

What must hold for a compiled-executable cache to be shippable:

* keys are stable across PROCESSES (a restarted worker addresses the
  same entry the dead one wrote) and sensitive to everything that
  changes the program (mesh, shardings, jax version, backend, config);
* a stale cache can never break (or silently corrupt) a boot — corrupt
  / truncated / wrong-version entries fall through to live compilation;
* a warm boot performs ZERO explicit XLA compiles and produces
  token-identical serving output;
* the bundle (weights + executables + tuned block sizes) round-trips.
"""

import json
import os
import pickle
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu import compile_cache as cc
from paddle_tpu.observability import default_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter_total(name: str, suffix: str = "") -> float:
    m = default_registry().get(name)
    if m is None:
        return 0.0
    return sum(c.value() for k, c in m.series()
               if not suffix or "/".join(k).endswith(suffix))


@pytest.fixture
def cache_env(tmp_path, monkeypatch):
    d = tmp_path / "exe_cache"
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", "1")
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR", str(d))
    cc.reset_memory()
    yield str(d)
    cc.reset_memory()


def _tiny_step(seed=0):
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pp.seed(seed)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    opt = pp.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, (2, 17)).astype(np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    return model, step, batch


# ---------------------------------------------------------------- keys
class TestKeys:
    def test_key_deterministic_and_sensitive(self):
        k1 = cc.cache_key("t", "sig", extra="e")
        assert k1 == cc.cache_key("t", "sig", extra="e")
        assert k1 != cc.cache_key("t2", "sig", extra="e")
        assert k1 != cc.cache_key("t", "sig2", extra="e")
        assert k1 != cc.cache_key("t", "sig", extra="e2")

    def test_mesh_and_shardings_change_key(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("dp", "tp"))
        mesh2 = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                     ("dp", "tp"))
        base = cc.cache_key("t", "sig")
        km = cc.cache_key("t", "sig", mesh=mesh)
        km2 = cc.cache_key("t", "sig", mesh=mesh2)
        assert len({base, km, km2}) == 3, \
            "mesh shape must be part of the address"
        sh1 = {"w": NamedSharding(mesh, P("dp"))}
        sh2 = {"w": NamedSharding(mesh, P("tp"))}
        ks1 = cc.cache_key("t", "sig", mesh=mesh, shardings=sh1)
        ks2 = cc.cache_key("t", "sig", mesh=mesh, shardings=sh2)
        assert ks1 != ks2, "sharding mismatch must be a MISS, not a hit"

    def test_model_config_tag_sees_baked_constants(self):
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        pp.seed(0)
        m1 = LlamaForCausalLM(LlamaConfig.tiny())
        pp.seed(0)
        m2 = LlamaForCausalLM(LlamaConfig.tiny(rope_theta=123.0))
        # identical param avals, different rope tables baked at trace
        # time -> the config tag is what keeps them apart
        assert cc.model_config_tag(m1) != cc.model_config_tag(m2)

    @pytest.mark.slow  # subprocess boot; the CI cold-start gate runs it
    def test_key_stable_across_processes(self, tmp_path):
        """The content address a fresh process computes for the same
        TrainStep signature must equal ours — that IS the cache."""
        model, step, batch = _tiny_step()
        from paddle_tpu.observability.device_profiler import signature_of
        placed = step._place_batch(batch)
        lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
        sig = signature_of(((step.params, step.opt_state, step.step_count,
                             placed, step._key, lr), {}))
        key = cc.cache_key("TrainStep(LlamaForCausalLM)", sig,
                           extra=step._cache_extra())
        script = textwrap.dedent("""
            import sys
            sys.path.insert(0, %r)
            sys.path.insert(0, %r)
            from _jax_platform import force_cpu_default
            force_cpu_default(min_devices=8)
            import numpy as np
            import jax.numpy as jnp
            import paddle_tpu as pp
            from paddle_tpu import compile_cache as cc
            from paddle_tpu.jit import TrainStep
            from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
            from paddle_tpu.observability.device_profiler import \\
                signature_of
            pp.seed(0)
            model = LlamaForCausalLM(LlamaConfig.tiny())
            opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
            step = TrainStep(model, opt)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, 256, (2, 17)).astype(np.int32)
            batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
            placed = step._place_batch(batch)
            lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
            sig = signature_of(((step.params, step.opt_state,
                                 step.step_count, placed, step._key, lr),
                                {}))
            print(cc.cache_key("TrainStep(LlamaForCausalLM)", sig,
                               extra=step._cache_extra()))
        """) % (REPO, os.path.join(REPO, "tests"))
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=300,
                             env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, out.stderr[-2000:]
        assert out.stdout.strip().splitlines()[-1] == key


# ---------------------------------------------------- entry validation
class TestInvalidation:
    def _store_one(self, cache_env):
        f = jax.jit(lambda x: x * 3 + 1)
        x = jnp.ones((16,), jnp.float32)
        compiled, info, hit = cc.aot_compile_cached(f, x, target="inv")
        assert not hit
        files = [n for n in os.listdir(cache_env) if n.endswith(".exe")]
        assert len(files) == 1
        return f, x, os.path.join(cache_env, files[0])

    def test_truncated_entry_falls_through(self, cache_env):
        f, x, path = self._store_one(cache_env)
        raw = open(path, "rb").read()
        open(path, "wb").write(raw[: len(raw) // 3])
        cc.reset_memory()
        compiled, info, hit = cc.aot_compile_cached(f, x, target="inv")
        assert not hit and not info.cached       # live compile
        assert float(compiled(x)[0]) == 4.0
        assert not os.path.exists(path) or \
            os.path.getsize(path) > len(raw) // 3  # stale file replaced

    def test_corrupt_payload_counts_deserialize_error(self, cache_env):
        f, x, path = self._store_one(cache_env)
        entry = pickle.load(open(path, "rb"))
        entry["payload"] = entry["payload"][: len(entry["payload"]) // 2]
        pickle.dump(entry, open(path, "wb"))
        cc.reset_memory()
        before = _counter_total("paddle_tpu_compile_cache_total",
                                "deserialize_error")
        compiled, info, hit = cc.aot_compile_cached(f, x, target="inv")
        after = _counter_total("paddle_tpu_compile_cache_total",
                               "deserialize_error")
        assert not hit
        assert after == before + 1
        assert float(compiled(x)[0]) == 4.0      # boot survived

    def test_wrong_jax_version_is_a_miss(self, cache_env):
        f, x, path = self._store_one(cache_env)
        entry = pickle.load(open(path, "rb"))
        entry["jax_version"] = "0.0.1"
        pickle.dump(entry, open(path, "wb"))
        cc.reset_memory()
        compiled, info, hit = cc.aot_compile_cached(f, x, target="inv")
        assert not hit and not info.cached

    def test_wrong_backend_is_a_miss(self, cache_env):
        f, x, path = self._store_one(cache_env)
        entry = pickle.load(open(path, "rb"))
        entry["backend"] = "tpu:TPU_v5_lite:n8"   # CPU must never serve it
        pickle.dump(entry, open(path, "wb"))
        cc.reset_memory()
        compiled, info, hit = cc.aot_compile_cached(f, x, target="inv")
        assert not hit and not info.cached

    def test_disabled_writes_nothing(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", "0")
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR",
                           str(tmp_path / "off"))
        cc.reset_memory()
        f = jax.jit(lambda x: x + 1)
        compiled, info, hit = cc.aot_compile_cached(
            f, jnp.ones((4,)), target="off")
        assert not hit
        assert not os.path.isdir(str(tmp_path / "off")) or \
            not os.listdir(str(tmp_path / "off"))


# ------------------------------------------------------------ TrainStep
class TestTrainStepCache:
    def test_compile_hits_and_matches_live_loss(self, cache_env):
        model, step, batch = _tiny_step()
        info = step.compile(batch)
        assert not info.cached
        live_loss = float(step(batch))
        before = _counter_total("paddle_tpu_compile_total")
        cc.reset_memory()
        model2, step2, batch2 = _tiny_step()
        info2 = step2.compile(batch2)
        assert info2.cached, "second process-equivalent boot must hit"
        assert _counter_total("paddle_tpu_compile_total") == before, \
            "a cache hit must not perform an explicit XLA compile"
        from paddle_tpu.observability.tracing import tracer
        names = {s["name"] for s in tracer().finished_spans()}
        assert "compile.cache_hit" in names, \
            "the hit must run under its tracer span"
        assert float(step2(batch2)) == live_loss

    def test_plain_call_adopts_cached_executable(self, cache_env):
        model, step, batch = _tiny_step()
        step.compile(batch)
        live_loss = float(step(batch))
        cc.reset_memory()
        model2, step2, batch2 = _tiny_step()
        # never calls compile(): the first __call__ probes the cache
        loss = float(step2(batch2))
        assert step2._compiled is not None, \
            "transparent cold-start adoption must install the executable"
        assert loss == live_loss


# -------------------------------------------------------------- serving
def _engine(model):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(model, slots=2, max_len=64,
                                    prefill_buckets=(16,))


class TestServingWarmup:
    def test_cached_vs_live_token_identical(self, cache_env):
        model, _, _ = _tiny_step()
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 256, (7,)).astype(np.int32)

        with _engine(model) as eng:
            stats = eng.aot_warmup()
            assert set(stats) == {"serving.decode", "serving.insert",
                                  "serving.prefill[16]"}
            rid = eng.add_request(prompt, max_new_tokens=6)
            live = eng.run()[rid][1]

        cc.reset_memory()
        before = _counter_total("paddle_tpu_compile_total")
        with _engine(model) as eng2:
            stats2 = eng2.aot_warmup()
            assert set(stats2) == set(stats)
            assert _counter_total("paddle_tpu_compile_total") == before, \
                "warm-cache warmup must perform zero XLA compiles"
            assert eng2._decode_compiled is not None
            assert eng2._insert_compiled is not None
            rid = eng2.add_request(prompt, max_new_tokens=6)
            cached = eng2.run()[rid][1]
        assert cached == live, "cached executables changed the tokens"

    def test_paged_warmup_round_trips(self, cache_env):
        model, _, _ = _tiny_step()
        from paddle_tpu.inference.serving import ContinuousBatchingEngine

        def build():
            return ContinuousBatchingEngine(
                model, slots=2, max_len=64, prefill_buckets=(16,),
                paged_kv=True, kv_block_size=8, prefill_chunk=16,
                spec_decode=2)
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, 256, (9,)).astype(np.int32)
        with build() as eng:
            stats = eng.aot_warmup()
            assert "serving.prefill_chunk[16]" in stats
            assert "serving.spec_verify" in stats
            rid = eng.add_request(prompt, max_new_tokens=5)
            live = eng.run()[rid][1]
        cc.reset_memory()
        before = _counter_total("paddle_tpu_compile_total")
        with build() as eng2:
            assert set(eng2.aot_warmup()) == set(stats)
            assert _counter_total("paddle_tpu_compile_total") == before
            rid = eng2.add_request(prompt, max_new_tokens=5)
            assert eng2.run()[rid][1] == live

    def test_recover_consults_cache_after_fault(self, cache_env):
        """Chaos: an engine that was NEVER warmed takes an engine-step
        fault; _recover must come back holding the cached executables
        (zero-compile restart-after-fault boot)."""
        from paddle_tpu import robustness
        model, _, _ = _tiny_step()
        with _engine(model) as warmer:
            warmer.aot_warmup()              # populate the cache
        cc.reset_memory()
        before = _counter_total("paddle_tpu_compile_total")
        rng = np.random.default_rng(3)
        robustness.reset_registry()
        try:
            with _engine(model) as eng:
                assert eng._decode_compiled is None
                rid = eng.add_request(rng.integers(0, 256, (5,)),
                                      max_new_tokens=4)
                eng.step()                   # admission + prefill
                robustness.inject("serving.engine_step", times=1)
                eng.step()                   # fault fires -> _recover
                assert eng.request_status(rid) == "error"
                assert eng._decode_compiled is not None, \
                    "_recover must adopt cached executables"
                assert _counter_total(
                    "paddle_tpu_compile_total") == before
                # the engine still serves, through the cached programs
                rid2 = eng.add_request(rng.integers(0, 256, (5,)),
                                       max_new_tokens=3)
                out = eng.run()
                assert len(out[rid2][1]) >= 1
        finally:
            robustness.reset_registry()


# --------------------------------------------------------------- bundle
class TestBundle:
    def test_round_trip(self, cache_env, tmp_path, monkeypatch):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at.json"))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_SEED", "0")
        at.reload()
        at._put("flash", "bundle-test-key@cpu-interpret", (128, 128, True))
        at._save()

        f = jax.jit(lambda x: x * 2)
        x = jnp.ones((8,), jnp.float32)
        cc.aot_compile_cached(f, x, target="bundle.exe")
        weights = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                   "b": np.ones((3,), np.float32)}

        out = tmp_path / "artifact"
        man = cc.bundle(str(out), state_dict=weights)
        assert man["checkpoint"] == "checkpoint"
        assert len(man["executables"]) == 1
        assert man["autotune_entries"] >= 1
        assert os.path.exists(out / "MANIFEST.json")

        # fresh machine: empty caches, load the bundle
        dest = tmp_path / "dest_cache"
        monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE_DIR", str(dest))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "at2.json"))
        at.reload()
        cc.reset_memory()
        res = cc.load_bundle(str(out))
        assert res["installed"] == ["bundle.exe"]
        assert res["autotune_entries"] >= 1
        np.testing.assert_array_equal(res["state_dict"]["w"],
                                      weights["w"])
        # installed executable actually serves
        compiled, info, hit = cc.aot_compile_cached(f, x,
                                                    target="bundle.exe")
        assert hit and info.cached
        assert float(compiled(x).sum()) == 16.0
        # tuned block sizes visible through the autotune cache
        assert "flash|bundle-test-key@cpu-interpret" in at.cached_entries()
        at.reload()

    def test_load_bundle_rejects_garbage(self, tmp_path):
        with pytest.raises(ValueError):
            cc.load_bundle(str(tmp_path / "nope"))
        bad = tmp_path / "bad"
        bad.mkdir()
        (bad / "MANIFEST.json").write_text('{"schema": 999}')
        with pytest.raises(ValueError):
            cc.load_bundle(str(bad))

    def test_cli_stats_and_clear(self, cache_env, capsys):
        f = jax.jit(lambda x: x + 5)
        cc.aot_compile_cached(f, jnp.ones((4,)), target="cli")
        assert cc.main(["stats"]) == 0
        out = capsys.readouterr().out
        assert "cli" in out
        assert cc.main(["clear"]) == 0
        assert cc.cached_entries() == []


# -------------------------------------------------------------- elastic
class TestElasticRestart:
    @pytest.mark.slow  # two worker-process boots; CI gate runs it
    def test_generation_restart_boots_from_cache(self, tmp_path):
        """Elastic chaos: generation 0 compiles (populating the cache)
        and dies; the restarted generation must boot its TrainStep with
        ZERO explicit XLA compiles — the restart-after-fault cold start
        ROADMAP 5 promises."""
        from paddle_tpu.distributed.elastic import ElasticManager
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent("""
            import json, os, sys
            sys.path.insert(0, %r)
            sys.path.insert(0, %r)
            os.environ["JAX_PLATFORMS"] = "cpu"
            from _jax_platform import force_cpu_default
            force_cpu_default(min_devices=8)
            import numpy as np
            import paddle_tpu as pp
            from paddle_tpu.distributed import ElasticAgent
            from paddle_tpu.jit import TrainStep
            from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
            from paddle_tpu.observability import default_registry
            agent = ElasticAgent(interval=0.2)
            gen = int(os.environ["PADDLE_ELASTIC_GEN"])
            pp.seed(0)
            model = LlamaForCausalLM(LlamaConfig.tiny())
            opt = pp.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
            step = TrainStep(model, opt)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, 256, (2, 17)).astype(np.int32)
            batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
            info = step.compile(batch)
            loss = float(step(batch))
            m = default_registry().get("paddle_tpu_compile_total")
            compiles = sum(c.value() for _k, c in m.series()) if m else 0
            out = sys.argv[1]
            with open(os.path.join(out, f"gen{gen}.json"), "w") as f:
                json.dump({"cached": bool(info.cached), "loss": loss,
                           "compiles": compiles}, f)
            agent.stop()
            os._exit(1 if gen == 0 else 0)
        """) % (REPO, os.path.join(REPO, "tests")))
        env = {
            "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                "PYTHONPATH", ""),
            "PADDLE_TPU_COMPILE_CACHE": "1",
            "PADDLE_TPU_COMPILE_CACHE_DIR": str(tmp_path / "cache"),
        }
        mgr = ElasticManager(
            [sys.executable, str(script), str(tmp_path)], nproc=1,
            max_restarts=2, env=env)
        try:
            rc = mgr.run()
        finally:
            mgr.close()
        assert rc == 0
        g0 = json.load(open(tmp_path / "gen0.json"))
        g1 = json.load(open(tmp_path / "gen1.json"))
        assert g0["cached"] is False and g0["compiles"] >= 1
        assert g1["cached"] is True, \
            "restarted generation must hit the executable cache"
        assert g1["compiles"] == 0, \
            "restarted generation must perform zero XLA compiles"
        assert g1["loss"] == g0["loss"]
