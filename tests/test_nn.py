"""nn.Layer + layer zoo tests (modelled on the reference's OpTest/numpy-parity
style, SURVEY.md §4)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def test_linear_forward_shape_and_value():
    pt.seed(1)
    layer = nn.Linear(4, 3)
    x = pt.randn([2, 4])
    y = layer(x)
    assert y.shape == [2, 3]
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-5)


def test_layer_registration_and_state_dict():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)

        def forward(self, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = Net()
    names = dict(net.named_parameters())
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    sd = net.state_dict()
    assert len(sd) == 4
    # roundtrip
    net2 = Net()
    missing, unexpected = net2.set_state_dict(sd)
    assert not missing and not unexpected
    np.testing.assert_allclose(net2.fc1.weight.numpy(), net.fc1.weight.numpy())


def test_layer_backward_through_net():
    net = nn.Sequential(nn.Linear(3, 5), nn.ReLU(), nn.Linear(5, 1))
    x = pt.randn([4, 3])
    loss = net(x).mean()
    loss.backward()
    for p in net.parameters():
        assert p.grad is not None, "missing grad"


def test_conv2d_matches_numpy():
    import torch  # cpu torch available for reference conv
    import torch.nn.functional as TF
    pt.seed(0)
    x = np.random.randn(1, 3, 8, 8).astype(np.float32)
    w = np.random.randn(5, 3, 3, 3).astype(np.float32)
    b = np.random.randn(5).astype(np.float32)
    out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w), pt.to_tensor(b),
                   stride=2, padding=1)
    ref = TF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    stride=2, padding=1).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_conv2d_transpose_matches_torch():
    import torch
    import torch.nn.functional as TF
    x = np.random.randn(1, 4, 5, 5).astype(np.float32)
    w = np.random.randn(4, 6, 3, 3).astype(np.float32)  # [in, out, kh, kw]
    out = F.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w), stride=2,
                             padding=1)
    ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=1).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_batchnorm_running_stats():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = pt.randn([4, 3, 2, 2]) * 3 + 1
    bn.train()
    _ = bn(x)
    # running mean moved toward batch mean
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    y = bn(x)
    assert y.shape == [4, 3, 2, 2]


def test_layernorm_and_rmsnorm():
    ln = nn.LayerNorm(8)
    x = pt.randn([2, 4, 8])
    y = ln(x)
    m = y.numpy().mean(-1)
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-5)
    rn = nn.RMSNorm(8)
    y2 = rn(x)
    assert y2.shape == [2, 4, 8]


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = pt.ones([1000])
    d.train()
    y = d(x)
    zeros = float((y.numpy() == 0).mean())
    assert 0.3 < zeros < 0.7
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = pt.to_tensor(np.array([0, 3, 0], np.int32))
    out = emb(idx)
    np.testing.assert_allclose(out.numpy()[0], np.zeros(4))
    np.testing.assert_allclose(out.numpy()[2], np.zeros(4))


def test_multi_head_attention():
    pt.seed(0)
    mha = nn.MultiHeadAttention(16, 4)
    x = pt.randn([2, 5, 16])
    y = mha(x)
    assert y.shape == [2, 5, 16]
    y.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    enc_layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
    enc = nn.TransformerEncoder(enc_layer, 2)
    x = pt.randn([2, 6, 16])
    y = enc(x)
    assert y.shape == [2, 6, 16]


def test_lstm_scan():
    lstm = nn.LSTM(input_size=4, hidden_size=8, num_layers=1)
    x = pt.randn([2, 7, 4])  # [B, T, D]
    out, _ = lstm(x)
    assert out.shape == [2, 7, 8]
    out.sum().backward()
    assert lstm.rnns[0].cell.weight_ih.grad is not None


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(2, 3), nn.Tanh(), nn.Linear(3, 2))
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(list(ll.parameters())) == 6


def test_pooling():
    x = pt.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y = F.max_pool2d(x, 2)
    np.testing.assert_allclose(y.numpy()[0, 0], [[5, 7], [13, 15]])
    y2 = F.avg_pool2d(x, 2)
    np.testing.assert_allclose(y2.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    y3 = F.adaptive_avg_pool2d(x, 1)
    np.testing.assert_allclose(y3.numpy()[0, 0], [[7.5]])


def test_cross_entropy_matches_torch():
    import torch
    import torch.nn.functional as TF
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, size=(6,))
    out = F.cross_entropy(pt.to_tensor(logits),
                          pt.to_tensor(labels.astype(np.int32)))
    ref = TF.cross_entropy(torch.tensor(logits), torch.tensor(labels)).numpy()
    np.testing.assert_allclose(float(out), ref, rtol=1e-5)


def test_cross_entropy_ignore_index_and_smoothing():
    import torch
    import torch.nn.functional as TF
    logits = np.random.randn(6, 10).astype(np.float32)
    labels = np.random.randint(0, 10, size=(6,))
    labels[0] = -100
    out = F.cross_entropy(pt.to_tensor(logits),
                          pt.to_tensor(labels.astype(np.int32)),
                          ignore_index=-100, label_smoothing=0.1)
    ref = TF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                           ignore_index=-100, label_smoothing=0.1).numpy()
    np.testing.assert_allclose(float(out), ref, rtol=1e-4)


def test_grad_clip_global_norm():
    clip = nn.ClipGradByGlobalNorm(1.0)
    p1 = pt.Parameter(np.zeros(3, np.float32))
    p2 = pt.Parameter(np.zeros(2, np.float32))
    g1 = pt.to_tensor(np.array([3.0, 0.0, 0.0], np.float32))
    g2 = pt.to_tensor(np.array([0.0, 4.0], np.float32))
    out = clip([(p1, g1), (p2, g2)])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_functional_call_under_jit():
    import jax
    from paddle_tpu.core.functional import functional_call, params_of

    net = nn.Sequential(nn.Linear(3, 4), nn.Tanh(), nn.Linear(4, 1))
    params = params_of(net)

    @jax.jit
    def loss_fn(params, x):
        out = functional_call(net, params, x)
        return (out ** 2).mean()

    x = pt.randn([5, 3])._data
    l1 = loss_fn(params, x)
    grads = jax.grad(loss_fn)(params, x)
    assert set(grads) == set(params)
    # eager forward must equal functional forward
    l2 = float((net(pt.Tensor._wrap(x)) ** 2).mean())
    np.testing.assert_allclose(float(l1), l2, rtol=1e-5)
