"""Driver-contract coverage: entry() compiles, dryrun_multichip shards the
full train step over an 8-device mesh (conftest forces the virtual CPU mesh)."""

import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")


def test_entry_compiles():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 1024
    assert np.isfinite(np.asarray(out).sum())


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as g
    g.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_1():
    import __graft_entry__ as g
    g.dryrun_multichip(1)
