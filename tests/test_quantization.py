"""Quantization tests: observers, fake-quant STE, PTQ calibrate/convert,
QAT train/convert (reference: test/quantization/)."""

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.quantization import (AbsMaxObserver, FakeQuantLinear,
                                     MovingAverageAbsMaxObserver, PTQ, QAT,
                                     QuantConfig, QuantedLinear,
                                     quant_dequant, quantize_weight)


class TestQuantMath:
    def test_quant_dequant_roundtrip_error_bounded(self):
        import jax.numpy as jnp
        x = pp.randn([64])
        scale = jnp.asarray(float(np.abs(x.numpy()).max()) / 127.0)
        y = quant_dequant(x, scale)
        err = np.abs(y.numpy() - x.numpy()).max()
        assert err <= float(scale) / 2 + 1e-7

    def test_ste_gradient_passes_through(self):
        import jax, jax.numpy as jnp
        scale = jnp.asarray(0.1)

        def f(v):
            return quant_dequant(v, scale).sum()
        g = jax.grad(f)(jnp.asarray([0.5, -0.3, 100.0]))
        np.testing.assert_allclose(np.asarray(g), [1.0, 1.0, 0.0])

    def test_quantize_weight_per_channel(self):
        w = pp.randn([8, 4])
        q, scale = quantize_weight(w, axis=1)
        assert q.dtype == np.int8 and scale.shape == (1, 4)
        deq = np.asarray(q, np.float32) * np.asarray(scale)
        assert np.abs(deq - w.numpy()).max() < np.abs(w.numpy()).max() / 64


class TestObservers:
    def test_absmax(self):
        obs = AbsMaxObserver()
        obs(pp.to_tensor([1.0, -3.0]))
        obs(pp.to_tensor([2.0]))
        assert obs.scale() == pytest.approx(3.0 / 127)

    def test_moving_average(self):
        obs = MovingAverageAbsMaxObserver(moving_rate=0.5)
        obs(pp.to_tensor([4.0]))
        obs(pp.to_tensor([2.0]))
        assert obs._absmax == pytest.approx(3.0)


def _mlp():
    pp.seed(3)
    return pp.nn.Sequential(pp.nn.Linear(8, 32), pp.nn.ReLU(),
                            pp.nn.Linear(32, 4))


class TestPTQ:
    def test_calibrate_convert_accuracy(self):
        net = _mlp()
        x = pp.randn([16, 8])
        ref = net(x).numpy()

        ptq = PTQ()
        net = ptq.quantize(net)
        for _ in range(4):  # calibration passes
            net(x)
        net = ptq.convert(net)
        # converted layers are int8
        assert isinstance(net[0], QuantedLinear)
        assert net[0].qweight.numpy().dtype == np.int8
        out = net(x).numpy()
        # int8 PTQ: small relative error on this scale of net
        rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.1, rel


class TestQAT:
    def test_fake_quant_trains_and_converts(self):
        net = _mlp()
        qat = QAT()
        net = qat.quantize(net)
        assert isinstance(net[0], FakeQuantLinear)

        opt = pp.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
        x = pp.randn([32, 8])
        y = pp.to_tensor((np.arange(32) % 4).astype(np.int64))

        losses = []
        for _ in range(20):
            out = net(x)
            loss = pp.nn.functional.cross_entropy(out, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

        net = qat.convert(net)
        assert isinstance(net[0], QuantedLinear)
        out = net(x)
        assert tuple(out.shape) == (32, 4)

    def test_weight_only_quanted_linear(self):
        lin = pp.nn.Linear(16, 8)
        q = QuantedLinear(lin, act_scale=None)
        x = pp.randn([4, 16])
        np.testing.assert_allclose(q(x).numpy(), lin(x).numpy(),
                                   rtol=0.1, atol=0.05)
