"""dy2static AST control-flow capture (reference: python/paddle/jit/
dy2static transformer pipeline — ifelse_transformer, loop_transformer).

One Python source must serve BOTH eager execution and jit tracing:
data-dependent if/while/for-range become lax.cond / lax.while_loop when
the condition is traced, plain Python when concrete.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pp
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import convert_to_static


class TestIfConversion:
    def test_traced_if(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(np.ones(3, np.float32)))), 2.0)
        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(-np.ones(3, np.float32)))), -2.0)

    def test_if_partial_assignment_uses_outer(self):
        @to_static
        def f(x):
            y = x * 0.0
            if x.sum() > 0:
                y = x + 10.0
            return y

        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(np.ones(2, np.float32)))), 11.0)
        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(-np.ones(2, np.float32)))), 0.0)

    def test_nested_if(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                if x.sum() > 10:
                    y = x * 3.0
                else:
                    y = x * 2.0
            else:
                y = -x
            return y

        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(np.full(2, 8.0, np.float32)))), 24.0)
        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray(np.ones(2, np.float32)))), 2.0)

    def test_one_armed_if_new_local_concrete_cond(self):
        # a local introduced only inside a one-armed if must behave like
        # python when the (concrete) condition is false: unbound afterwards
        @to_static
        def f(x):
            if x.shape[0] > 2:
                big = x.sum() * 0.0 + 1.0
            y = x * 2
            return y

        np.testing.assert_allclose(
            np.asarray(f(jnp.ones((1, 3), jnp.float32))), 2.0)
        np.testing.assert_allclose(
            np.asarray(f(jnp.ones((4, 3), jnp.float32))), 2.0)

    def test_one_armed_if_unbound_read_still_raises(self):
        @to_static
        def f(x):
            if x.shape[0] > 2:
                big = x.sum()
            return big  # unbound when the branch is not taken

        with pytest.raises((NameError, UnboundLocalError)):
            f(jnp.ones((1, 3), jnp.float32))

    def test_read_before_store_unbound_raises_not_zero(self):
        # both branches ASSIGN y, but one READS it first — with no outer
        # binding the traced path must raise, not compute with a silent 0
        @to_static
        def f(x):
            if x.sum() > 0:
                y = y + x.sum()  # noqa: F821 — deliberate unbound read
            else:
                y = x.sum()
            return y

        with pytest.raises(TypeError, match="no prior definition"):
            f(jnp.ones(3, jnp.float32))

    def test_comprehension_in_branch_not_hoisted(self):
        # a comprehension's target is comprehension-scoped (py3) — it must
        # not be treated as a branch-local needing a pre-if definition
        @to_static
        def f(x):
            if x.sum() > 0:
                ys = sum([i * 1.0 for i in range(3)])
            else:
                ys = 0.0
            return x.sum() + ys

        np.testing.assert_allclose(float(f(jnp.ones(3, jnp.float32))), 6.0)

    def test_read_before_store_with_outer_binding_ok(self):
        @to_static
        def f(x):
            y = x.sum()
            if x.shape[0] > 1:
                y = y + 1.0
            else:
                y = y - 1.0
            return y

        np.testing.assert_allclose(float(f(jnp.ones(3, jnp.float32))), 4.0)

    def test_eager_tensor_condition(self):
        # same source runs eagerly on Tensors (python branch taken)
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        g = convert_to_static(f)
        t = pp.to_tensor(np.ones(3, np.float32))
        np.testing.assert_allclose(g(t).numpy(), 2.0)

    def test_return_inside_assigning_if(self):
        # early return inside an assigning if: rewritten to flag+value
        @to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2
                return y
            else:
                y = -x
            return y

        np.testing.assert_allclose(np.asarray(f(jnp.ones(2))), 2.0)
        np.testing.assert_allclose(np.asarray(f(-jnp.ones(2))), 1.0)

    def test_plain_guard_return_left_untransformed(self):
        # assignment-free if with return stays Python: concrete conditions
        # keep working after conversion (guard-clause pattern)
        def f(x, flag):
            if flag:
                return x * 2
            return -x

        g = convert_to_static(f)
        np.testing.assert_allclose(
            np.asarray(g(jnp.ones(2, jnp.float32), True)), 2.0)
        np.testing.assert_allclose(
            np.asarray(g(jnp.ones(2, jnp.float32), False)), -1.0)


class TestWhileConversion:
    def test_traced_while(self):
        @to_static
        def g(x):
            n = jnp.zeros((), jnp.int32)
            while x.sum() > 1.0:
                x = x * 0.5
                n = n + 1
            return n

        out = int(np.asarray(g(jnp.asarray(np.full(4, 8.0, np.float32)))))
        # 32 -> 16 -> 8 -> 4 -> 2 -> 1: five halvings to reach sum <= 1
        assert out == 5

    def test_while_under_explicit_jit(self):
        # the converted while must be jit-traceable end to end
        @to_static
        def g(x):
            while x.sum() > 1.0:
                x = x * 0.5
            return x.sum()

        out = float(np.asarray(g(jnp.asarray(np.full(2, 4.0,
                                                     np.float32)))))
        np.testing.assert_allclose(out, 1.0, rtol=1e-6)  # 8->4->2->1

    def test_break_exits_loop(self):
        @to_static
        def f(x):
            while x.sum() > 0:
                x = x - 1
                break
            return x

        np.testing.assert_allclose(np.asarray(f(jnp.ones(2))), 0.0)


class TestForConversion:
    def test_for_traced_bound(self):
        @to_static
        def h(x, steps):
            acc = jnp.zeros_like(x)
            for i in range(steps):
                acc = acc + x * (i + 1)
            return acc

        out = np.asarray(h(jnp.asarray(np.ones(2, np.float32)), 3))
        np.testing.assert_allclose(out, 6.0)  # 1+2+3
        out = np.asarray(h(jnp.asarray(np.ones(2, np.float32)), 5))
        np.testing.assert_allclose(out, 15.0)

    def test_for_python_iterable_unrolls(self):
        @to_static
        def h(x):
            for w in [1.0, 2.0, 3.0]:
                x = x * w
            return x

        np.testing.assert_allclose(
            np.asarray(h(jnp.asarray(np.ones(2, np.float32)))), 6.0)


class TestNoSourceFallback:
    def test_lambda_passthrough(self):
        f = to_static(lambda x: x * 2)
        np.testing.assert_allclose(
            np.asarray(f(jnp.ones(2, jnp.float32))._data
                       if hasattr(f(jnp.ones(2, jnp.float32)), "_data")
                       else f(jnp.ones(2, jnp.float32))), 2.0)


class TestReviewRegressions:
    def test_negative_step_range(self):
        @to_static
        def f(x, n):
            acc = x * 0.0
            for i in range(n, 0, -1):
                acc = acc + i
            return acc

        np.testing.assert_allclose(
            np.asarray(f(jnp.zeros(1, jnp.float32), 3)), 6.0)

    def test_while_body_local_temp_eager(self):
        def f(x):
            while x.sum() > 1.0:
                t = x * 0.5
                x = t
            return x

        g = convert_to_static(f)
        out = g(pp.to_tensor(np.full(2, 4.0, np.float32)))
        np.testing.assert_allclose(out.numpy(), 0.5)

    def test_while_body_local_temp_traced_now_seeds(self):
        # a body-local temp written before read needs no pre-loop value:
        # while_call seeds a typed placeholder (was a loud error before
        # the early-exit work made seeding safe)
        @to_static
        def f(x):
            while x.sum() > 1.0:
                t = x * 0.5
                x = t
            return x

        out = f(jnp.full(2, 4.0, jnp.float32))
        np.testing.assert_allclose(np.asarray(out), 0.5)

    def test_layer_tuple_output(self):
        class TwoOut(pp.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = pp.nn.Linear(3, 3)

            def forward(self, x):
                h = self.lin(x)
                return h, (h * 2).sum()

        m = to_static(TwoOut())
        out, aux = m(pp.randn([2, 3]))
        assert tuple(out.shape) == (2, 3)
        assert np.isfinite(float(aux.numpy()))


class TestEarlyExit:
    """break/continue/return in converted blocks (VERDICT r2 item 8;
    reference break_continue_transformer.py / return_transformer.py)."""

    def _check(self, fn, *args, want):
        got = fn(*args)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_while_break_concrete(self):
        @to_static
        def f(x):
            i = 0
            while i < 10:
                x = x + 1.0
                i = i + 1
                if i >= 3:
                    break
            return x

        self._check(f, jnp.zeros(2), want=3.0)

    def test_while_continue_concrete(self):
        @to_static
        def f(x):
            i = 0
            while i < 6:
                i = i + 1
                if i % 2 == 0:
                    continue
                x = x + 1.0  # only odd iterations
            return x

        self._check(f, jnp.zeros(2), want=3.0)

    def test_for_range_break(self):
        @to_static
        def f(x):
            for i in range(10):
                if i == 4:
                    break
                x = x + 1.0
            return x

        self._check(f, jnp.zeros(2), want=4.0)

    def test_for_range_continue_still_advances(self):
        @to_static
        def f(x):
            for i in range(6):
                if i % 2 == 1:
                    continue
                x = x + 1.0  # i = 0, 2, 4
            return x

        self._check(f, jnp.zeros(2), want=3.0)

    def test_traced_while_break_on_data(self):
        # break condition depends on TRACED data -> lax.while_loop path
        @to_static
        def f(x):
            i = 0.0
            while i < 100.0:
                x = x - 0.5
                i = i + 1.0
                if x.sum() < 0:
                    break
            return x

        out = f(jnp.ones(2))
        assert float(np.asarray(out).sum()) < 0

    def test_return_from_loop(self):
        @to_static
        def f(x):
            for i in range(10):
                x = x + 1.0
                if i == 2:
                    return x
            return x - 100.0

        self._check(f, jnp.zeros(2), want=3.0)

    def test_return_both_arms_traced(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                return x * 2.0
            else:
                return -x

        out = f(jnp.ones(3))
        np.testing.assert_allclose(np.asarray(out), 2.0)
        out2 = f(-jnp.ones(3))
        np.testing.assert_allclose(np.asarray(out2), 1.0)

    def test_code_after_return_is_skipped(self):
        @to_static
        def f(x):
            if x.shape[0] > 1:
                return x + 1.0
            x = x * 100.0
            return x

        self._check(f, jnp.zeros(2), want=1.0)
        self._check(f, jnp.zeros(1), want=0.0)

    def test_multi_target_assignment_in_branch(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                a, b = x * 2.0, x * 3.0
            else:
                a, b = -x, x
            return a + b

        np.testing.assert_allclose(np.asarray(f(jnp.ones(2))), 5.0)

    def test_nested_loop_break_binds_inner(self):
        @to_static
        def f(x):
            for i in range(3):
                for j in range(5):
                    if j == 1:
                        break  # inner only
                    x = x + 1.0
            return x

        self._check(f, jnp.zeros(2), want=3.0)

    def test_return_in_loop_fires_on_first_match(self):
        # review regression: the loop must STOP at the first firing
        # return, not keep iterating and take the last match
        @to_static
        def f(x):
            for i in range(8):
                if x[i] > 0:
                    return x[i] * (i + 1.0)
            return x[0] * 0.0

        v = np.zeros(8, np.float32)
        v[2] = 1.0
        v[5] = 1.0
        np.testing.assert_allclose(float(f(jnp.asarray(v))), 3.0)

    def test_scalar_int_return_both_arms(self):
        # review regression: int returns must not be seeded with a float
        # placeholder under traced conditions
        @to_static
        def g(x):
            if x.sum() > 0:
                return 1
            return 2

        assert int(np.asarray(g(jnp.ones(3)))) == 1
        assert int(np.asarray(g(-jnp.ones(3)))) == 2


class TestBreakContinueReturnParity:
    """VERDICT r3 #7: break/continue in converted loops and early return
    lowering, each checked for parity against the eager (unconverted)
    execution of the same source."""

    def test_break_in_traced_while_parity(self):
        def f(x, n):
            i = 0
            while i < n:          # traced bound -> lax.while_loop
                x = x + 1
                i = i + 1
                if x.sum() > 10:
                    break
            return x

        want = f(jnp.ones(4), 20)          # eager: python loop
        got = jax.jit(convert_to_static(f))(jnp.ones(4), jnp.asarray(20))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_continue_in_for_parity(self):
        def f(x, n):
            acc = x * 0
            for i in range(n):
                if i % 2 == 0:
                    continue
                acc = acc + i
            return acc

        want = f(jnp.zeros(()), 6)
        got = jax.jit(convert_to_static(f))(jnp.zeros(()), jnp.asarray(6))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_mixed_continue_break_parity(self):
        def f(x, n):
            total = x * 0
            for i in range(n):
                if i == 1:
                    continue
                if i >= 4:
                    break
                total = total + i
            return total

        want = f(jnp.zeros(()), 10)
        got = jax.jit(convert_to_static(f))(jnp.zeros(()), jnp.asarray(10))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_early_return_in_traced_for_parity(self):
        def f(x, n):
            for i in range(n):
                x = x + 1
                if x.sum() > 5:
                    return x * 100
            return x

        want = f(jnp.ones(2), 10)
        got = jax.jit(convert_to_static(f))(jnp.ones(2), jnp.asarray(10))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_return_inside_while_parity(self):
        def f(x):
            while x.sum() < 100:
                x = x * 2
                if x.sum() > 50:
                    return x + 0.5
            return x

        want = f(jnp.ones(3))
        got = jax.jit(convert_to_static(f))(jnp.ones(3))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_tuple_and_aug_assign_in_branch(self):
        def f(x, flag):
            a, b = x, x * 2
            if flag.sum() > 0:
                a += 1
                a, b = b, a
            return a + b

        want = f(jnp.ones(()), jnp.ones(2))
        got = jax.jit(convert_to_static(f))(jnp.ones(()), jnp.ones(2))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


class TestContainerState:
    """Container mutation inside converted compounds (reference
    list_transformer.py / dict assignment handling): append and item
    assignment are functionalized so containers ride the carries."""

    def test_list_append_concrete_loop_under_jit(self):
        def f(x):
            acc = []
            for i in range(4):
                acc.append(x * (i + 1))
            return pp.stack(acc) if hasattr(pp, "stack") else jnp.stack(acc)

        want = np.asarray(jnp.stack([jnp.ones(3) * k for k in (1, 2, 3, 4)]))
        got = jax.jit(convert_to_static(f))(jnp.ones(3, jnp.float32))
        got = got._data if hasattr(got, "_data") else got
        np.testing.assert_allclose(np.asarray(got), want)

    def test_list_append_inside_traced_if(self):
        """Both branches append ONE element: structure stays stable so
        lax.cond carries the list fine."""
        def f(x):
            acc = [x]
            if x.sum() > 0:
                acc.append(x * 2)
            else:
                acc.append(x - 1)
            return acc[0] + acc[1]

        conv = convert_to_static(f)
        np.testing.assert_allclose(
            np.asarray(jax.jit(conv)(jnp.ones(3, jnp.float32))), 3.0)
        np.testing.assert_allclose(
            np.asarray(jax.jit(conv)(-jnp.ones(3, jnp.float32))), -3.0)

    def test_dict_state_concrete_loop(self):
        def f(x):
            state = {"sum": x * 0.0, "count": 0}
            for i in range(5):
                state["sum"] = state["sum"] + x
                state["count"] += 1
            return state["sum"] / state["count"]

        got = jax.jit(convert_to_static(f))(jnp.full(2, 3.0))
        np.testing.assert_allclose(np.asarray(got), 3.0)

    def test_dict_state_traced_while(self):
        """Stable dict keys thread through a TRACED while carry."""
        def f(x):
            state = {"acc": x * 0.0, "i": 0.0}
            while state["acc"].sum() < 10.0:
                state["acc"] = state["acc"] + x
                state["i"] += 1.0
            return state["i"]

        want = f(jnp.full(2, 1.0))
        got = jax.jit(convert_to_static(f))(jnp.full(2, 1.0))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_list_append_traced_while_raises_clearly(self):
        def f(x):
            acc = []
            while x.sum() < 10.0:
                acc.append(x)
                x = x + 1.0
            return x

        with pytest.raises(TypeError, match="grow|structure|append"):
            jax.jit(convert_to_static(f))(jnp.zeros(2, jnp.float32))

    def test_list_setitem_in_branch(self):
        def f(x):
            slots = [x * 0.0, x * 0.0]
            if x.sum() > 0:
                slots[0] = x
            else:
                slots[1] = x
            return slots[0] - slots[1]

        conv = convert_to_static(f)
        np.testing.assert_allclose(
            np.asarray(jax.jit(conv)(jnp.ones(2))), 1.0)
        np.testing.assert_allclose(
            np.asarray(jax.jit(conv)(-jnp.ones(2))), 1.0)

    def test_eager_semantics_preserved(self):
        """Concrete data: the same source behaves like plain Python."""
        def f(x):
            acc = []
            d = {}
            for i in range(3):
                acc.append(i * x)
                d[i] = i
            return acc, d

        acc, d = convert_to_static(f)(2.0)
        assert acc == [0.0, 2.0, 4.0]
        assert d == {0: 0, 1: 1, 2: 2}

    def test_aliasing_caveat_is_name_scoped(self):
        """The functional rewrite rebinds the NAME; a top-level append
        before any compound still truly mutates."""
        def f(x):
            acc = []
            acc.append(x)          # top-level: real mutation
            for i in range(2):
                acc.append(x + i)  # in-loop: functional rebind
            return len(acc)

        assert convert_to_static(f)(1.0) == 3


class TestNestedDefsAndTry:
    """r4 Weak #5 residue: nested function defs and try/except in
    converted code — locked in as SUPPORTED (with the one documented
    rejection: a def escaping a traced branch)."""

    def test_nested_def_called_in_traced_branches(self):
        def f(x):
            def scale(v, k):
                return v * k
            out = x
            if x.sum() > 0:
                out = scale(x, 2.0)
            else:
                out = scale(x, -1.0)
            return out

        g = jax.jit(convert_to_static(f))
        np.testing.assert_allclose(np.asarray(g(jnp.ones(3))), 2.0)
        np.testing.assert_allclose(np.asarray(g(-jnp.ones(3))), 1.0)

    def test_try_except_with_traced_if(self):
        def f(x):
            try:
                y = x / (x.sum() + 1.0)
            except ZeroDivisionError:
                y = x
            if y.sum() > 0:
                y = y * 2
            return y

        got = jax.jit(convert_to_static(f))(jnp.ones(2))
        np.testing.assert_allclose(np.asarray(got), 2.0 / 3.0, rtol=1e-6)

    def test_return_inside_try_inside_traced_if(self):
        def f(x):
            if x.sum() > 0:
                try:
                    return x * 2
                except ValueError:
                    return x
            return x - 1

        g = jax.jit(convert_to_static(f))
        np.testing.assert_allclose(np.asarray(g(jnp.ones(2))), 2.0)
        np.testing.assert_allclose(np.asarray(g(-jnp.ones(2))), -2.0)

    def test_try_inside_traced_while(self):
        def f(x):
            acc = x * 0.0
            while acc.sum() < 10.0:
                try:
                    acc = acc + x
                except RuntimeError:
                    break
            return acc

        got = jax.jit(convert_to_static(f))(jnp.full(2, 1.0))
        np.testing.assert_allclose(np.asarray(got), 5.0)

    def test_def_only_used_inside_concrete_branch_ok(self):
        """A def consumed entirely within a concrete-condition branch
        stays plain Python and works."""
        def f(x, flag=True):
            out = x
            if flag:
                def twice(v):
                    return v * 2
                out = twice(x)
            return out

        np.testing.assert_allclose(
            np.asarray(convert_to_static(f)(jnp.ones(3))), 2.0)

    def test_def_escaping_converted_branch_fails_at_use(self):
        """A def whose NAME escapes a CONVERTED if fails at the use site
        (function values cannot ride a lax.cond carry) — pinned so the
        failure mode stays a nameable error, not silence."""
        def f(x):
            if x.sum() > 0:
                y = 1.0

                def op(v):
                    return v * 2
            else:
                y = 2.0

                def op(v):
                    return v - 1
            return op(x) + y

        with pytest.raises((NameError, NotImplementedError)):
            jax.jit(convert_to_static(f))(jnp.ones(3))
