"""Continuous-batching serving engine (VERDICT r4 Weak #4 / Next #6):
slot reuse, bucketed prefill, per-slot positions, int8 weight-only mode
— all CPU-runnable, parity-checked against model.generate."""

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.inference.serving import (ContinuousBatchingEngine,
                                          quantize_weights_int8)


@pytest.fixture(scope="module")
def tiny_model():
    pp.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=256, hidden_size=64,
                           intermediate_size=128, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


def _reference(model, prompt, n):
    out = model.generate(np.asarray(prompt, np.int32)[None],
                         max_new_tokens=n, do_sample=False)
    return list(np.asarray(out)[0, len(prompt):])


class TestContinuousBatching:
    def test_single_request_matches_generate(self, tiny_model):
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 256, (12,))
        eng = ContinuousBatchingEngine(tiny_model, slots=2, max_len=64,
                                       prefill_buckets=(16, 32))
        rid = eng.add_request(prompt, max_new_tokens=8)
        results = eng.run()
        assert results[rid][1] == _reference(tiny_model, prompt, 8)

    @pytest.mark.slow
    def test_slot_reuse_more_requests_than_slots(self, tiny_model):
        """5 requests through 2 slots: all finish, all match the
        sequential generate oracle, different prompt lengths exercise
        both prefill buckets."""
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 256, (n,))
                   for n in (5, 13, 17, 9, 30)]
        eng = ContinuousBatchingEngine(tiny_model, slots=2, max_len=64,
                                       prefill_buckets=(16, 32))
        rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        results = eng.run()
        assert len(results) == 5
        for rid, p in zip(rids, prompts):
            assert results[rid][1] == _reference(tiny_model, p, 6), \
                f"request {rid} (len {len(p)}) diverged"

    def test_streaming_admission(self, tiny_model):
        """Requests added WHILE others decode still complete correctly
        (the continuous part of continuous batching)."""
        rng = np.random.default_rng(2)
        eng = ContinuousBatchingEngine(tiny_model, slots=2, max_len=64,
                                       prefill_buckets=(16,))
        first = rng.integers(0, 256, (8,))
        r0 = eng.add_request(first, max_new_tokens=10)
        for _ in range(4):
            eng.step()
        late = rng.integers(0, 256, (6,))
        r1 = eng.add_request(late, max_new_tokens=4)
        results = eng.run()
        assert results[r0][1] == _reference(tiny_model, first, 10)
        assert results[r1][1] == _reference(tiny_model, late, 4)

    def test_eos_frees_slot_early(self, tiny_model):
        """A sequence hitting EOS retires its slot; the next queued
        request then runs in it."""
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 256, (8,))
        ref = _reference(tiny_model, prompt, 12)
        eos = ref[3]  # force an early stop at a token we know appears
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=64,
                                       prefill_buckets=(16,),
                                       eos_token_id=eos)
        r0 = eng.add_request(prompt, max_new_tokens=12)
        p2 = rng.integers(0, 256, (7,))
        r1 = eng.add_request(p2, max_new_tokens=3)
        results = eng.run()
        assert results[r0][1] == ref[:4]      # stopped AT the eos token
        assert len(results[r1][1]) == 3       # second request ran after

    def test_bucket_overflow_rejected(self, tiny_model):
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=64,
                                       prefill_buckets=(16,))
        with pytest.raises(ValueError, match="bucket"):
            eng.add_request(np.zeros(20, np.int32), max_new_tokens=2)
        with pytest.raises(ValueError, match="reserved"):
            eng.add_request(np.zeros(10, np.int32), max_new_tokens=60)


class TestInt8Serving:
    def test_quantize_split(self, tiny_model):
        from paddle_tpu.core.functional import params_of
        params = params_of(tiny_model)
        keep, quant = quantize_weights_int8(params, min_size=1024)
        assert quant, "no weights selected for int8"
        for name, (w8, scale) in quant.items():
            assert w8.dtype == np.int8 and int(np.abs(w8).max()) <= 127
            # dequantized weight close to original (per-channel symmetric)
            deq = np.asarray(w8, np.float32) * np.asarray(scale)
            orig = np.asarray(params[name], np.float32)
            err = np.abs(deq - orig).max() / (np.abs(orig).max() + 1e-9)
            assert err < 0.02, (name, err)

    def test_int8_decode_runs_and_stays_close(self, tiny_model):
        """int8 weight-only decode produces a plausible continuation:
        identical first tokens to bf16 greedy for a short horizon (tiny
        model, 1% weight error — argmax ties aside this should hold for
        the first few steps)."""
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, 256, (10,))
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=64,
                                       prefill_buckets=(16,),
                                       int8_weights=True)
        rid = eng.add_request(prompt, max_new_tokens=4)
        results = eng.run()
        assert len(results[rid][1]) == 4
        assert all(0 <= t < 256 for t in results[rid][1])


class TestChunkedDecode:
    def test_steps_per_sync_parity(self, tiny_model):
        """K decode steps fused per host sync produce the SAME tokens as
        step-by-step decode (and as model.generate)."""
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, 256, (n,)) for n in (6, 11, 14)]
        eng = ContinuousBatchingEngine(tiny_model, slots=2, max_len=64,
                                       prefill_buckets=(16,),
                                       steps_per_sync=4)
        rids = [eng.add_request(p, max_new_tokens=7) for p in prompts]
        results = eng.run()
        for rid, p in zip(rids, prompts):
            assert results[rid][1] == _reference(tiny_model, p, 7), \
                f"chunked decode diverged for request {rid}"

    def test_chunk_headroom_enforced(self, tiny_model):
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=32,
                                       prefill_buckets=(16,),
                                       steps_per_sync=8)
        with pytest.raises(ValueError, match="rounded"):
            eng.add_request(np.zeros(16, np.int32), max_new_tokens=10)

    def test_constructor_validation(self, tiny_model):
        with pytest.raises(ValueError, match="RoPE"):
            ContinuousBatchingEngine(tiny_model, max_len=4096,
                                     prefill_buckets=(16,))
        with pytest.raises(ValueError, match="bucket"):
            ContinuousBatchingEngine(tiny_model, max_len=16,
                                     prefill_buckets=(16,))

    def test_train_mode_restored_on_close(self, tiny_model):
        tiny_model.train()
        try:
            with ContinuousBatchingEngine(tiny_model, slots=1, max_len=48,
                                          prefill_buckets=(8,)) as eng:
                assert not tiny_model.training
                rid = eng.add_request(np.arange(6), max_new_tokens=2)
                eng.run()
            assert tiny_model.training
        finally:
            tiny_model.eval()


class TestPagedKnobRegression:
    """PADDLE_TPU_PAGED_KV=0 (or unset) must reproduce the exact
    previous engine; =1 must be token-for-token greedy-identical.
    (The paged engine's own suite lives in tests/test_kv_cache.py.)"""

    def test_default_is_unpaged(self, tiny_model, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PAGED_KV", raising=False)
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=64,
                                       prefill_buckets=(16,))
        assert not eng.paged
        assert hasattr(eng, "_caches")       # slot-contiguous buffers

    def test_knob_zero_matches_knob_one(self, tiny_model, monkeypatch):
        rng = np.random.default_rng(40)
        prompt = rng.integers(0, 256, (12,))
        outs = {}
        for knob in ("0", "1"):
            monkeypatch.setenv("PADDLE_TPU_PAGED_KV", knob)
            eng = ContinuousBatchingEngine(
                tiny_model, slots=2, max_len=64, prefill_buckets=(16,))
            assert eng.paged == (knob == "1")
            rid = eng.add_request(prompt, max_new_tokens=8)
            outs[knob] = eng.run()[rid][1]
        assert outs["0"] == outs["1"]
        assert outs["0"] == _reference(tiny_model, prompt, 8)


class TestSampling:
    def test_near_zero_temperature_matches_greedy(self, tiny_model):
        """do_sample with temperature -> 0 degenerates to argmax: exact
        parity with the greedy reference at every step."""
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, 256, (9,))
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=48,
                                       prefill_buckets=(16,),
                                       do_sample=True, temperature=1e-6)
        rid = eng.add_request(prompt, max_new_tokens=6)
        results = eng.run()
        assert results[rid][1] == _reference(tiny_model, prompt, 6)

    def test_sampling_varies_with_seed_and_stays_in_vocab(self, tiny_model):
        rng = np.random.default_rng(12)
        prompt = rng.integers(0, 256, (8,))
        outs = []
        for seed in (0, 1):
            eng = ContinuousBatchingEngine(
                tiny_model, slots=1, max_len=48, prefill_buckets=(16,),
                do_sample=True, temperature=1.0, top_k=50, seed=seed)
            rid = eng.add_request(prompt, max_new_tokens=12)
            outs.append(eng.run()[rid][1])
        assert all(0 <= t < 256 for o in outs for t in o)
        assert outs[0] != outs[1], "two seeds produced identical samples"

    def test_sampled_chunked_decode(self, tiny_model):
        """Sampling + steps_per_sync compose (key threads the scan)."""
        rng = np.random.default_rng(13)
        eng = ContinuousBatchingEngine(
            tiny_model, slots=2, max_len=48, prefill_buckets=(16,),
            do_sample=True, temperature=0.8, top_p=0.95, seed=3,
            steps_per_sync=4)
        rids = [eng.add_request(rng.integers(0, 256, (n,)), 8)
                for n in (6, 10)]
        results = eng.run()
        assert all(len(results[r][1]) == 8 for r in rids)
