"""Stage-3 milestone: single-chip E2E training of the flagship Llama stack
through the jitted TrainStep (SURVEY.md §7 step 3)."""

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.jit import TrainStep, to_static
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def _batch(cfg, batch=4, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1))
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = pp.to_tensor(np.zeros((2, 8), np.int32))
    logits = model(ids)
    assert tuple(logits.shape) == (2, 8, cfg.vocab_size)


def test_llama_gqa_and_tied():
    cfg = LlamaConfig.tiny(num_key_value_heads=1, tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    ids = pp.to_tensor(np.zeros((2, 8), np.int32))
    logits = model(ids)
    assert tuple(logits.shape) == (2, 8, cfg.vocab_size)
    assert model.lm_head is None


@pytest.mark.slow
def test_llama_kv_cache_matches_full_forward():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (1, 6))
    full = model(pp.to_tensor(ids)).numpy()

    import jax.numpy as jnp
    caches = [(jnp.zeros((1, 0, cfg.num_key_value_heads, cfg.head_dim)),
               jnp.zeros((1, 0, cfg.num_key_value_heads, cfg.head_dim)))
              for _ in range(cfg.num_hidden_layers)]
    outs = []
    for t in range(6):
        logits, caches = model(pp.to_tensor(ids[:, t:t + 1]), caches=caches,
                               position_offset=t)
        outs.append(logits.numpy())
    step = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(full, step, rtol=2e-4, atol=2e-4)


def test_llama_prefill_then_decode_matches_full_forward():
    """Prefill (multi-token query over cache) must stay causal."""
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, (1, 8))
    full = model(pp.to_tensor(ids)).numpy()

    import jax.numpy as jnp
    caches = [(jnp.zeros((1, 0, cfg.num_key_value_heads, cfg.head_dim)),
               jnp.zeros((1, 0, cfg.num_key_value_heads, cfg.head_dim)))
              for _ in range(cfg.num_hidden_layers)]
    prefill, caches = model(pp.to_tensor(ids[:, :5]), caches=caches)
    np.testing.assert_allclose(full[:, :5], prefill.numpy(),
                               rtol=2e-4, atol=2e-4)
    step1, caches = model(pp.to_tensor(ids[:, 5:6]), caches=caches,
                          position_offset=5)
    np.testing.assert_allclose(full[:, 5:6], step1.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_rope_table_overflow_raises():
    cfg = LlamaConfig.tiny(max_position_embeddings=16)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 32), np.int32)
    with pytest.raises(ValueError, match="RoPE table overflow"):
        model(pp.to_tensor(ids))


def test_train_step_scheduler_checkpoint_roundtrip():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    sched = pp.optimizer.lr.StepDecay(learning_rate=1e-2, step_size=2,
                                      gamma=0.5)
    opt = pp.optimizer.SGD(learning_rate=sched, parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = _batch(cfg)
    for _ in range(3):
        step(batch)
    snap = step.state_dict()
    lr_before = opt.get_lr()

    model2 = LlamaForCausalLM(cfg)
    sched2 = pp.optimizer.lr.StepDecay(learning_rate=1e-2, step_size=2,
                                       gamma=0.5)
    opt2 = pp.optimizer.SGD(learning_rate=sched2,
                            parameters=model2.parameters())
    step2 = TrainStep(model2, opt2)
    step2.set_state_dict(snap)
    assert opt2.get_lr() == lr_before


def test_train_step_loss_decreases():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-2,
                             parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = _batch(cfg)
    losses = [float(step(batch)) for _ in range(12)]
    assert losses[-1] < losses[0] * 0.8, losses
    # params written back into the Layer
    before = model.state_dict(keep_vars=True)[
        "model.embed_tokens.weight"].numpy().copy()
    step.sync_to_model()
    after = model.state_dict(keep_vars=True)[
        "model.embed_tokens.weight"].numpy()
    assert not np.allclose(before, after)


def test_train_step_lr_schedule_applied():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    sched = pp.optimizer.lr.StepDecay(learning_rate=1e-2, step_size=1,
                                      gamma=0.0)  # lr → 0 after first step
    opt = pp.optimizer.SGD(learning_rate=sched, parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = _batch(cfg)
    step(batch)
    p1 = {n: np.asarray(a) for n, a in step.params.items()}
    step(batch)  # lr == 0 now: nothing may move
    for n, a in step.params.items():
        np.testing.assert_allclose(np.asarray(a), p1[n], rtol=0, atol=0)


def test_train_step_remat():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.SGD(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, opt, remat=True)
    assert np.isfinite(float(step(_batch(cfg))))


@pytest.mark.slow
def test_train_step_checkpoint_roundtrip():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
    step = TrainStep(model, opt)
    batch = _batch(cfg)
    step(batch)
    snap = step.state_dict()
    l1 = float(step(batch))

    model2 = LlamaForCausalLM(cfg)
    opt2 = pp.optimizer.AdamW(learning_rate=1e-3,
                              parameters=model2.parameters())
    step2 = TrainStep(model2, opt2)
    step2.set_state_dict(snap)
    l2 = float(step2(batch))
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_to_static_layer():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((2, 8), np.int32)
    eager = model(pp.to_tensor(ids)).numpy()
    compiled = to_static(model)
    static = compiled(pp.to_tensor(ids)).numpy()
    np.testing.assert_allclose(eager, static, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_bf16_model_trains():
    cfg = LlamaConfig.tiny(dtype="bfloat16")
    model = LlamaForCausalLM(cfg)
    opt = pp.optimizer.AdamW(learning_rate=1e-2,
                             parameters=model.parameters(),
                             multi_precision=True)
    step = TrainStep(model, opt)
    losses = [float(step(_batch(cfg))) for _ in range(8)]
    assert losses[-1] < losses[0], losses
