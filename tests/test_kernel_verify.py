"""Pallas/Mosaic kernel static verifier (analysis/kernel_verify).

Covers: the catalog-wide clean sweep at bench shapes (incl. the two
named megakernel Mosaic risks surfacing as WARNINGs), adversarial
KernelSpec fixtures that each trip exactly the intended finding code,
the shared VMEM footprint model backing the megakernel eligibility
gate, autotune candidate pruning (the sub-quantum quant row-block class
is provably rejected before benchmarking), the odd-vocab CE block
clamp, the registered ``kernel-verify`` pass over a traced pallas_call
program, and the ``lint --kernels`` CLI verdict table.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis import kernel_verify as kv
from paddle_tpu.analysis.diagnostics import Severity
from paddle_tpu.ops.pallas import fused_block as FB


def codes_of(diags):
    return sorted({d.message.split(":", 1)[0] for d in diags})


def error_codes_of(diags):
    return sorted({d.message.split(":", 1)[0] for d in diags
                   if d.severity >= Severity.ERROR})


# ---------------------------------------------------------------------------
# catalog: every shipped kernel x bench shape


class TestCatalog:
    @pytest.fixture(scope="class")
    def rows(self):
        return kv.catalog_report()

    def test_covers_all_seven_kernel_modules(self, rows):
        kernels = {r["kernel"] for r in rows}
        assert kernels >= {"flash_fwd", "flash_bwd", "fused_ce",
                           "rmsnorm", "fused_qkv", "fused_mlp",
                           "fused_decoder", "quant_matmul",
                           "paged_decode"}

    def test_catalog_has_zero_errors(self, rows):
        bad = [(r["kernel"], r["shape"], r["codes"]) for r in rows
               if r["errors"]]
        assert not bad, bad

    def test_decoder_named_risks_surface_as_distinct_warnings(self, rows):
        """Acceptance: the megakernel's lane-axis RoPE concat and the
        seq-scaling K/V scratch are each a distinct WARNING carrying the
        offending shape."""
        dec = [r for r in rows if r["kernel"] == "fused_decoder"]
        assert dec
        for r in dec:
            assert r["verdict"] == "WARNING", r
            assert set(r["codes"]) == {"LANE_CONCAT", "SEQ_SCRATCH"}, r
            seq = [d for d in r["diags"]
                   if d.message.startswith(kv.SEQ_SCRATCH)]
            # one finding per sequence-wide scratch buffer (K and V),
            # each naming the offending [s, dkv] shape
            assert len(seq) == 2
            assert any("(512, 512)" in d.message or
                       "(128, 1024)" in d.message for d in seq), \
                [d.message for d in seq]
            lane = [d for d in r["diags"]
                    if d.message.startswith(kv.LANE_CONCAT)]
            assert len(lane) == 1
            assert "lane" in lane[0].message

    def test_non_decoder_rows_are_clean(self, rows):
        for r in rows:
            if r["kernel"] != "fused_decoder":
                assert r["verdict"] == "OK", r

    def test_render_table_mentions_every_kernel(self, rows):
        table = kv.render_catalog_table(rows)
        for name in ("flash_fwd", "fused_decoder", "paged_decode"):
            assert name in table
        assert "0 error(s)" in table


# ---------------------------------------------------------------------------
# adversarial fixtures: each trips exactly the intended finding


def _spec(name="adv", grid=(4,), args=None, **kw):
    return kv.KernelSpec(name=name, grid=grid, args=args or [], **kw)


class TestAdversarialFixtures:
    def test_overlapping_output_index_map_is_write_race(self):
        # two parallel grid points write each output block
        spec = _spec(grid=(4,), args=[
            kv.ArgSpec("o", (256, 128), (128, 128),
                       lambda i: (i // 2, 0), "float32", is_output=True),
        ], dimension_semantics=("parallel",))
        diags = kv.verify_kernel(spec, record_metric=False)
        assert error_codes_of(diags) == [kv.WRITE_RACE], codes_of(diags)

    def test_sequential_revisit_is_not_a_race(self):
        # the same overlap along an "arbitrary" axis is the legal
        # accumulator pattern (flash dq, fused-MLP y) — no finding
        spec = _spec(grid=(4,), args=[
            kv.ArgSpec("o", (512, 128), (128, 128),
                       lambda i: (i // 2, 0), "float32", is_output=True),
        ], dimension_semantics=("arbitrary",))
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.WRITE_RACE not in codes_of(diags)
        # ...but full coverage is still required, and i//2 covers only
        # blocks 0..1 of 4
        assert kv.OUTPUT_UNCOVERED in error_codes_of(diags)

    def test_misaligned_lane_dim(self):
        spec = _spec(grid=(2,), args=[
            kv.ArgSpec("x", (16, 200), (16, 100), lambda i: (0, i),
                       "float32"),
        ])
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.LANE_MISALIGNED in error_codes_of(diags)

    def test_vmem_exceeding_block(self):
        spec = _spec(grid=(2,), args=[
            kv.ArgSpec("x", (16384, 1024), (8192, 1024), lambda i: (i, 0),
                       "float32"),
        ])
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.VMEM_EXCEEDED in error_codes_of(diags)

    def test_uncovered_output_block(self):
        spec = _spec(grid=(4,), args=[
            kv.ArgSpec("o", (512, 128), (128, 128), lambda i: (0, 0),
                       "float32", is_output=True),
        ], dimension_semantics=("arbitrary",))
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.OUTPUT_UNCOVERED in error_codes_of(diags)

    def test_oob_block_read(self):
        spec = _spec(grid=(4,), args=[
            kv.ArgSpec("x", (512, 128), (128, 128), lambda i: (i + 1, 0),
                       "float32"),
        ])
        diags = kv.verify_kernel(spec, record_metric=False)
        assert error_codes_of(diags) == [kv.OOB_BLOCK], codes_of(diags)

    def test_redundant_dma_on_dma_once_arg(self):
        # the inner sweep leaves weight block 0 and comes back (j % 2):
        # Pallas must re-DMA it — exactly what the fused-block clamped
        # maps exist to avoid
        spec = _spec(grid=(1, 4), args=[
            kv.ArgSpec("w", (256, 128), (128, 128),
                       lambda i, j: (j % 2, 0), "float32", dma_once=True),
            kv.ArgSpec("o", (128, 128), (128, 128),
                       lambda i, j: (i, 0), "float32", is_output=True),
        ], dimension_semantics=("parallel", "arbitrary"))
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.REDUNDANT_DMA in codes_of(diags)
        assert not error_codes_of(diags)

    def test_clamped_map_passes_dma_once(self):
        # the fused-qkv wq map: resident for the first half of the inner
        # sweep, clamped after — each block DMAs exactly once per sweep
        spec = _spec(grid=(2, 4), args=[
            kv.ArgSpec("w", (256, 256), (256, 128),
                       FB._clamped(0, 2), "float32", dma_once=True),
            kv.ArgSpec("o", (256, 128), (128, 128),
                       lambda i, j: (i, 0), "float32", is_output=True),
        ], dimension_semantics=("parallel", "arbitrary"))
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.REDUNDANT_DMA not in codes_of(diags)

    def test_block_indivisible(self):
        spec = _spec(grid=(2,), args=[
            kv.ArgSpec("x", (300, 128), (128, 128), lambda i: (i, 0),
                       "float32"),
        ])
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.BLOCK_INDIVISIBLE in error_codes_of(diags)

    def test_missing_fp32_accumulator_warns(self):
        spec = _spec(grid=(2,), args=[
            kv.ArgSpec("x", (256, 128), (128, 128), lambda i: (i, 0),
                       "bfloat16"),
        ], needs_fp32_acc=True)
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.ACC_DTYPE in codes_of(diags)

    def test_quant_scale_shape_mismatch(self):
        from paddle_tpu.ops.pallas import quant_matmul as qm
        diags = qm.verify_static(256, 1024, 1024, block_t=128,
                                 block_n=256)
        assert not error_codes_of(diags)
        # break the agreement: scale lanes frozen at 128 vs qw's 256
        spec = _spec(grid=(2, 4), args=[
            kv.ArgSpec("qw", (256, 1024), (256, 256),
                       lambda i, j: (0, j), "int8"),
            kv.ArgSpec("scale", (1, 1024), (1, 128),
                       lambda i, j: (0, j), "float32"),
        ], scale_pairs=[("scale", "qw")])
        diags = kv.verify_kernel(spec, record_metric=False)
        assert kv.SCALE_SHAPE in error_codes_of(diags)


# ---------------------------------------------------------------------------
# the shared VMEM footprint model (satellite: megakernel gate unification)


class TestSharedVmemModel:
    def test_decoder_budget_is_the_verifier_budget(self):
        assert FB._DECODER_VMEM_BUDGET == kv.VMEM_BUDGET_BYTES

    def test_decoder_vmem_bytes_delegates_to_footprint_model(self):
        a = (512, 1024, 1024, 512, 128, 3584, 64, 128, 128, "bfloat16")
        spec = FB._decoder_verify_spec(1, *a)
        assert FB.decoder_vmem_bytes(*a) == kv.footprint_bytes(spec)

    def test_footprint_monotone_in_seq(self):
        lo = FB.decoder_vmem_bytes(128, 1024, 1024, 512, 128, 3584,
                                   16, 128, 128, "bfloat16")
        hi = FB.decoder_vmem_bytes(4096, 1024, 1024, 512, 128, 3584,
                                   16, 128, 128, "bfloat16")
        assert hi > lo

    def test_eligibility_gate_and_lint_verdict_agree(self):
        """The gate admits a shape iff verify_static finds no
        VMEM ERROR for it (they share the same footprint + budget)."""
        for shape in [(4, 512, 1024, 1024, 512, 128, 3584),
                      (4, 2048, 2048, 2048, 1024, 128, 7168)]:
            b, s, d, dq, dkv, hd, f = shape
            eligible = FB.fused_decoder_eligible(b, s, d, dq, dkv, hd, f,
                                                 "bfloat16")
            diags = FB.verify_static_decoder(b, s, d, dq, dkv, hd, f,
                                             dtype="bfloat16")
            vmem_err = any(
                d.severity >= Severity.ERROR
                and d.message.startswith((kv.VMEM_EXCEEDED,))
                for d in diags)
            assert eligible == (not vmem_err), (shape, diags)

    def test_resident_args_count_single_buffered(self):
        spec = kv.KernelSpec(name="t", grid=(2,), args=[
            kv.ArgSpec("a", (256, 128), (128, 128), lambda i: (i, 0),
                       "float32"),
            kv.ArgSpec("w", (1, 128), (1, 128), lambda i: (0, 0),
                       "float32", resident=True),
        ])
        # a double-buffers (2x), resident w does not (1x)
        assert kv.footprint_bytes(spec) == \
            2 * 128 * 128 * 4 + 1 * 128 * 4


# ---------------------------------------------------------------------------
# autotune pruning (satellite: verify-before-bench)


class TestAutotunePruning:
    def test_quant_sub_quantum_row_blocks_are_pruned(self):
        """Acceptance: >= 1 illegal config class provably pruned — bf16
        activations at block_t=8 (sublane quantum is 16) never reach a
        benchmark."""
        from paddle_tpu.ops.pallas import autotune as at
        shape = (16, 1024, 1024, "int8", "bfloat16")
        cands = at._quant_candidates(*shape)
        assert any(bt == 8 for bt, _ in cands)    # the class exists...
        kept, n_pruned = kv.prune_candidates("quant_matmul", shape, cands)
        assert n_pruned == sum(bt == 8 for bt, _ in cands) > 0
        assert all(bt != 8 for bt, _ in kept)     # ...and is gone
        assert kept                                # but the set survives

    def test_prune_never_returns_empty(self):
        shape = (16, 1024, 1024, "int8", "bfloat16")
        only_bad = [(8, 128), (8, 256)]
        kept, n_pruned = kv.prune_candidates("quant_matmul", shape,
                                             only_bad)
        assert n_pruned == 2
        assert kept == only_bad    # wrongly-strict flag, not a crash

    def test_block_sizes_skip_pruned_candidates(self, monkeypatch,
                                                tmp_path):
        from paddle_tpu.ops.pallas import autotune as at
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                           str(tmp_path / "c.json"))
        at.reload()
        benched = []

        def fake(op_name, key, candidates, bench, default):
            benched.extend(candidates)
            return candidates[0]

        monkeypatch.setattr(at, "autotune", fake)
        at.quant_block_sizes(16, 1024, 1024, "int8", "bfloat16")
        assert benched and all(bt != 8 for bt, _ in benched)

    def test_ce_candidates_divide_odd_vocab(self):
        """Regression (satellite bugfix): enumerators must never emit a
        vocab block that does not divide V."""
        from paddle_tpu.ops.pallas import autotune as at
        from paddle_tpu.ops.pallas.cross_entropy import _default_blocks
        for t, v in [(64, 1000), (128, 4000), (64, 32000)]:
            for bt, bv in at._ce_candidates(t, v, "float32"):
                assert v % bv == 0, (t, v, bt, bv)
            assert v % _default_blocks(t, v)[1] == 0, (t, v)

    def test_default_quant_blocks_respect_sublane_quantum(self):
        from paddle_tpu.ops.pallas.quant_matmul import \
            _default_quant_blocks
        assert _default_quant_blocks(256, 1024, "bfloat16")[0] % 16 == 0
        # degenerate t keeps the old always-valid fallback
        assert _default_quant_blocks(8, 1024, "bfloat16") == (8, 512)

    def test_verify_only_sweep_exits_zero(self, capsys):
        from paddle_tpu.ops.pallas import autotune as at
        rc = at.main(["--sweep", "--verify-only", "--ops",
                      "quant_matmul,fused_ce"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pruned_invalid=3" in out
        assert "0 timed" in out


# ---------------------------------------------------------------------------
# the registered pass over a traced program


class TestKernelVerifyPass:
    def test_registered_but_not_default(self):
        from paddle_tpu.analysis.passes import DEFAULT_PASSES, all_passes
        assert "kernel-verify" in all_passes()
        assert "kernel-verify" not in DEFAULT_PASSES
        assert len(DEFAULT_PASSES) == 5

    def test_traced_pallas_call_is_verified(self):
        import paddle_tpu.analysis as analysis
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[:] = x_ref[:] * 2.0

        def f(x):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
                interpret=True,
            )(x)

        report = analysis.check(
            f, jax.ShapeDtypeStruct((256, 128), jnp.float32),
            passes=["kernel-verify"])
        found = report.by_pass("kernel-verify")
        assert found, report.format()
        assert not report.errors(), report.format()

    def test_traced_bad_index_map_is_flagged(self):
        import paddle_tpu.analysis as analysis
        from jax.experimental import pallas as pl

        def kern(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        def f(x):
            return pl.pallas_call(
                kern,
                grid=(4,),
                in_specs=[pl.BlockSpec((64, 128), lambda i: (i + 1, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
                interpret=True,
            )(x)

        report = analysis.check(
            f, jax.ShapeDtypeStruct((256, 128), jnp.float32),
            passes=["kernel-verify"])
        msgs = [d.message for d in report.errors()]
        assert any(m.startswith(kv.OOB_BLOCK) for m in msgs), \
            report.format()

    def test_program_without_pallas_is_informational(self):
        import paddle_tpu.analysis as analysis
        report = analysis.check(
            lambda x: x * 2, jax.ShapeDtypeStruct((8, 8), jnp.float32),
            passes=["kernel-verify"])
        assert not report.errors() and not report.warnings()
        assert any("no pallas_call" in d.message
                   for d in report.by_pass("kernel-verify"))


# ---------------------------------------------------------------------------
# observability + CLI


class TestSurface:
    def test_verify_metric_counts_verdicts(self):
        from paddle_tpu.observability import default_registry
        c = default_registry().counter(
            "paddle_tpu_kernel_verify_total",
            "static kernel verification outcomes",
            labelnames=("kernel", "verdict"))
        before = c.labels(kernel="rmsnorm_fwd", verdict="ok").value()
        from paddle_tpu.ops.pallas import rmsnorm as rn
        rn.verify_static(1024, 2048, "bfloat16")
        after = c.labels(kernel="rmsnorm_fwd", verdict="ok").value()
        assert after == before + 1

    def test_lint_kernels_cli(self, capsys):
        from paddle_tpu.analysis import lint
        rc = lint.main(["--kernels"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fused_decoder" in out and "verdict" in out
        assert "LANE_CONCAT" in out and "SEQ_SCRATCH" in out

    def test_lint_kernels_strict_fails_on_decoder_warnings(self):
        from paddle_tpu.analysis import lint
        assert lint.main(["--kernels", "--strict"]) == 1
