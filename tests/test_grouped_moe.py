"""Grouped expert-matmul Pallas kernel + PADDLE_TPU_GROUPED_MOE routing
(ISSUE 18 tentpole, layer 1).

Covers: interpret-mode fwd/bwd numerics of the grouped kernel against the
masked einsum reference (fp32 and bf16, full and partial ``counts``), the
exactly-zero contract for rows past a group's count, knob routing (off
restores the previous dense-einsum jaxpr byte-for-byte; on swaps in one
pallas_call) across every MoE dispatch mode, the static kernel-verify
catalog rows, autotune-v2 candidates/key/sweep plumbing, and the
cost-model bytes acceptance (< 0.5x of the dense einsum pair at the bench
shape).

Everything runs interpret-mode on CPU (conftest pins JAX_PLATFORMS).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

import paddle_tpu as pp  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.core.dispatch import unwrap  # noqa: E402
from paddle_tpu.ops.pallas import autotune as at  # noqa: E402
from paddle_tpu.ops.pallas import grouped_matmul as GM  # noqa: E402


def _weights(rng, E, d, h, dtype=jnp.float32):
    return (jnp.asarray(rng.standard_normal((E, d, h)) * 0.1, dtype),
            jnp.asarray(rng.standard_normal((E, h)) * 0.1, dtype),
            jnp.asarray(rng.standard_normal((E, h, d)) * 0.1, dtype),
            jnp.asarray(rng.standard_normal((E, d)) * 0.1, dtype))


def _tokens(rng, G, C, d, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal((G, C, d)), dtype)


# ---------------------------------------------------------------------------
# kernel numerics
# ---------------------------------------------------------------------------


class TestGroupedKernel:
    @pytest.mark.parametrize("G,C,d,h,E", [(4, 16, 8, 16, 4),
                                           (8, 16, 8, 16, 4),
                                           (2, 24, 16, 48, 2)])
    def test_fwd_matches_reference_full_counts(self, G, C, d, h, E):
        rng = np.random.default_rng(0)
        x = _tokens(rng, G, C, d)
        w1, b1, w2, b2 = _weights(rng, E, d, h)
        got = GM.grouped_expert_ffn(x, w1, b1, w2, b2)
        want = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_partial_counts_skip_and_zero(self):
        """Rows past a group's count come back exactly zero and the valid
        prefix matches the masked reference — the block-size-independent
        contract every dispatch path relies on."""
        rng = np.random.default_rng(1)
        G, C, d, h, E = 4, 16, 8, 16, 4
        x = _tokens(rng, G, C, d)
        w1, b1, w2, b2 = _weights(rng, E, d, h)
        counts = jnp.asarray([0, 3, 16, 9], jnp.int32)
        got = GM.grouped_expert_ffn(x, w1, b1, w2, b2, counts=counts,
                                    block_c=8, block_f=16)
        want = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2, counts)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        rows = np.arange(C)[None, :, None]
        pad = np.asarray(got) * (rows >= np.asarray(counts)[:, None, None])
        assert not pad.any()                     # exactly zero, not small

    def test_block_size_independent(self):
        rng = np.random.default_rng(2)
        G, C, d, h, E = 2, 32, 8, 32, 2
        x = _tokens(rng, G, C, d)
        w1, b1, w2, b2 = _weights(rng, E, d, h)
        counts = jnp.asarray([5, 32], jnp.int32)
        outs = [np.asarray(GM.grouped_expert_ffn(
            x, w1, b1, w2, b2, counts=counts, block_c=bc, block_f=bf))
            for bc, bf in [(8, 16), (16, 32), (32, 32)]]
        for o in outs[1:]:
            np.testing.assert_allclose(o, outs[0], rtol=2e-5, atol=2e-5)

    def test_bf16_matches_reference(self):
        rng = np.random.default_rng(3)
        G, C, d, h, E = 4, 16, 8, 16, 4
        x = _tokens(rng, G, C, d, jnp.bfloat16)
        w1, b1, w2, b2 = _weights(rng, E, d, h, jnp.bfloat16)
        counts = jnp.asarray([16, 7, 0, 12], jnp.int32)
        got = GM.grouped_expert_ffn(x, w1, b1, w2, b2, counts=counts)
        want = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2, counts)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=3e-2, atol=3e-2)

    def test_groups_replicate_expert_weights(self):
        """G > E: group g must use expert g // (G // E)'s weights (the
        all_to_all layout where each expert owns n_shards source chunks)."""
        rng = np.random.default_rng(4)
        G, C, d, h, E = 8, 8, 8, 16, 2
        x = _tokens(rng, G, C, d)
        w1, b1, w2, b2 = _weights(rng, E, d, h)
        got = GM.grouped_expert_ffn(x, w1, b1, w2, b2)
        want = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_masked_reference(self):
        rng = np.random.default_rng(5)
        G, C, d, h, E = 4, 16, 8, 16, 4
        x = _tokens(rng, G, C, d)
        w1, b1, w2, b2 = _weights(rng, E, d, h)
        counts = jnp.asarray([16, 3, 0, 10], jnp.int32)

        def loss_k(x, w1, b1, w2, b2):
            y = GM.grouped_expert_ffn(x, w1, b1, w2, b2, counts=counts)
            return (y.astype(jnp.float32) ** 2).sum()

        def loss_r(x, w1, b1, w2, b2):
            y = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2, counts)
            return (y.astype(jnp.float32) ** 2).sum()

        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(x, w1, b1, w2, b2)
        for name, a, b in zip("x w1 b1 w2 b2".split(), gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5,
                                       err_msg=name)

    def test_jit_and_counter(self):
        rng = np.random.default_rng(6)
        x = _tokens(rng, 4, 16, 8)
        w1, b1, w2, b2 = _weights(rng, 4, 8, 16)
        got = jax.jit(lambda *a: GM.grouped_expert_ffn(*a))(
            x, w1, b1, w2, b2)
        want = GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        from paddle_tpu.observability import default_registry
        c = default_registry().counter(
            "paddle_tpu_grouped_moe_path_total",
            "grouped expert-FFN implementation chosen at trace time",
            labelnames=("path",))
        before = c.labels(path="grouped").value()
        GM.record_path("grouped")
        assert c.labels(path="grouped").value() == before + 1


# ---------------------------------------------------------------------------
# knob routing: off restores the dense einsum jaxpr exactly
# ---------------------------------------------------------------------------


def _moe_layer(d=8, E=4, seed=0):
    pp.seed(seed)
    return dist.MoELayer(d_model=d, num_experts=E, d_hidden=16,
                         capacity_factor=2.0)


class TestKnobRouting:
    def _layer_jaxpr(self, monkeypatch, knob, dispatch_mode="einsum"):
        from paddle_tpu.core.functional import functional_call, params_of
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", knob)
        moe = _moe_layer()
        moe.dispatch_mode = dispatch_mode
        p = params_of(moe)
        x = jnp.zeros((2, 8, 8), jnp.float32)

        def f(p, x):    # fresh closure: make_jaxpr caches by identity
            return unwrap(functional_call(moe, p, pp.Tensor(x)))

        return str(jax.make_jaxpr(f)(p, x))

    @pytest.mark.parametrize("mode", ["einsum", "index"])
    def test_knob_off_restores_previous_jaxpr(self, monkeypatch, mode):
        """Acceptance: PADDLE_TPU_GROUPED_MOE unset/0 keeps the exact
        dense-einsum lowering — no pallas_call, byte-identical jaxpr
        before and after a knob-on trace; =1 routes one pallas_call."""
        j_base = self._layer_jaxpr(monkeypatch, "0", mode)
        j_on = self._layer_jaxpr(monkeypatch, "1", mode)
        j_off = self._layer_jaxpr(monkeypatch, "0", mode)
        assert "pallas_call" not in j_base
        assert "pallas_call" in j_on
        assert j_base == j_off

    @pytest.mark.parametrize("mode", ["einsum", "index"])
    def test_knob_on_parity(self, monkeypatch, mode):
        rng = np.random.default_rng(7)
        moe = _moe_layer()
        moe.dispatch_mode = mode
        x = pp.Tensor(jnp.asarray(
            rng.standard_normal((2, 8, 8)), jnp.float32))
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", "0")
        off = moe(x).numpy()
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", "1")
        on = moe(x).numpy()
        np.testing.assert_allclose(on, off, rtol=2e-5, atol=2e-5)

    def test_ineligible_shape_falls_back(self, monkeypatch):
        """G not divisible by E never reaches the kernel even knob-on."""
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", "1")
        assert not GM.grouped_ffn_eligible(3, 16, 8, 16, 2)
        assert GM.grouped_ffn_eligible(4, 16, 8, 16, 2)

    @pytest.mark.slow  # 8-way a2a traces x2; CI MoE gate runs it
    @pytest.mark.parametrize("mode", ["all_to_all", "all_to_all_index"])
    def test_knob_on_parity_a2a(self, monkeypatch, mode):
        pp.seed(8)
        d, E = 8, 8
        mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
        moe = dist.MoELayer(d_model=d, num_experts=E, d_hidden=16,
                            dispatch_mode=mode, mesh=mesh, dropless=True)
        x = pp.randn([2, 8, d])
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", "0")
        off = moe(x).numpy()
        monkeypatch.setenv("PADDLE_TPU_GROUPED_MOE", "1")
        on = moe(x).numpy()
        np.testing.assert_allclose(on, off, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# static verification + autotune plumbing
# ---------------------------------------------------------------------------


class TestStaticAndAutotune:
    def test_verify_static_clean_at_bench_shapes(self):
        from paddle_tpu.analysis import kernel_verify as kv
        for g, c, d, h, dtype in at.SWEEP_SHAPES["grouped_matmul"]:
            diags = GM.verify_static(g, c, d, h, dtype=dtype)
            assert kv.verdict_of(diags) == "ok", (
                (g, c, d, h), [d_.message for d_ in diags])

    def test_catalog_includes_grouped_rows(self):
        from paddle_tpu.analysis import kernel_verify as kv
        rows = [r for r in kv.catalog_report()
                if r["kernel"] == "grouped_matmul"]
        assert len(rows) >= 2
        for r in rows:
            assert r["verdict"] == "OK", r

    def test_candidates_prune_clean(self):
        g, c, d, h, dtype = at.SWEEP_SHAPES["grouped_matmul"][0]
        cands = at._grouped_candidates(g, c, d, h, dtype)
        assert cands
        kept, npruned = at._verify_prune(
            "grouped_matmul", (g, c, d, h, dtype), cands)
        assert npruned == 0         # every enumerated candidate is legal
        assert list(kept) == list(cands)
        for bc, bf in cands:
            assert c % bc == 0 and h % bf == 0

    def test_key_distinguishes_shapes_and_backend(self):
        k1 = at.grouped_key(8, 2560, 1024, 3584, "bfloat16",
                            interpret=True)
        k2 = at.grouped_key(8, 1280, 1024, 3584, "bfloat16",
                            interpret=True)
        assert k1 != k2 and "grouped" not in k1  # op name lives in _put
        assert k1.endswith("@" + at.backend_tag(interpret=True))

    def test_dry_sweep_persists_winner(self, monkeypatch, tmp_path):
        path = tmp_path / "autotune.json"
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", str(path))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_SEED", "0")
        at.reload()
        try:
            rc = at.main(["--sweep", "--dry-run", "--cache", str(path),
                          "--ops", "grouped_matmul"])
            assert rc == 0
            at.reload()
            entries = at.cached_entries()
            mine = {k: v for k, v in entries.items()
                    if k.startswith("grouped_matmul|")}
            assert len(mine) == len(at.SWEEP_SHAPES["grouped_matmul"])
            for val in mine.values():
                bc, bf = tuple(val)
                assert bc > 0 and bf > 0
        finally:
            monkeypatch.delenv("PADDLE_TPU_AUTOTUNE_CACHE")
            monkeypatch.delenv("PADDLE_TPU_AUTOTUNE_SEED")
            at.reload()


# ---------------------------------------------------------------------------
# cost model: < 0.5x dense-einsum bytes at the bench shape
# ---------------------------------------------------------------------------


class TestCostModelBytes:
    def _cost(self, fn, *args):
        from paddle_tpu.analysis import check
        rep = check(fn, *args, passes=["cost-model"])
        return rep.extras["cost"]

    def test_grouped_under_half_dense_bytes(self):
        """Acceptance: at the bench shape the grouped kernel's cost-model
        HBM bytes are < 0.5x the dense einsum pair — the [G, C, h] hidden
        intermediate never touches HBM."""
        g, c, d, h, dtype = at.SWEEP_SHAPES["grouped_matmul"][0]
        x = jnp.zeros((g, c, d), jnp.bfloat16)
        w1 = jnp.zeros((g, d, h), jnp.bfloat16)
        b1 = jnp.zeros((g, h), jnp.bfloat16)
        w2 = jnp.zeros((g, h, d), jnp.bfloat16)
        b2 = jnp.zeros((g, d), jnp.bfloat16)

        def grouped(x, w1, b1, w2, b2):
            return GM.grouped_expert_ffn(x, w1, b1, w2, b2)

        def dense(x, w1, b1, w2, b2):
            return GM.grouped_expert_ffn_reference(x, w1, b1, w2, b2)

        cg = self._cost(grouped, x, w1, b1, w2, b2)
        cd = self._cost(dense, x, w1, b1, w2, b2)
        assert cg.total_bytes < 0.5 * cd.total_bytes, \
            (cg.total_bytes, cd.total_bytes)
