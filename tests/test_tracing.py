"""Distributed tracing + SLO watchdog (ISSUE 5): span tree semantics,
head-based sampling, explicit context propagation across threads
(device_prefetch, dataloader, serving engine loop) and across a
simulated 2-worker TCPStore handoff, flight-recorder trace stamping +
snapshot, request_status timing fields, Perfetto export shape, watchdog
rule triggers over synthetic metric streams, and the Prometheus
cumulative-bucket exposition PromQL relies on."""

import json
import threading

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.observability import (MetricsRegistry, FlightRecorder,
                                      Tracer, Watchdog, flight_recorder,
                                      render_prometheus, tracer)
from paddle_tpu.observability.tracing import SpanContext
from paddle_tpu.observability.watchdog import (HeartbeatGapRule,
                                               QueueSaturationRule,
                                               RecompileStormRule,
                                               SkipStreakRule,
                                               StepTimeDriftRule,
                                               rules_from_spec)


@pytest.fixture()
def tr():
    """The process tracer (the one instrumentation writes to), cleared
    around each test so span assertions see only their own work."""
    t = tracer()
    t.clear()
    yield t
    t.clear()


# ------------------------------------------------------------ span basics
class TestSpanTree:
    def test_nesting_assigns_parent_and_shared_trace(self, tr):
        with tr.span("root", kind="outer") as root:
            with tr.span("child") as child:
                with tr.span("grandchild") as grand:
                    pass
        spans = {s["name"]: s for s in tr.finished_spans()}
        assert spans["child"]["parent_id"] == root.span_id
        assert spans["grandchild"]["parent_id"] == child.span_id
        assert len({s["trace_id"] for s in spans.values()}) == 1
        assert spans["root"]["attrs"]["kind"] == "outer"
        assert grand.trace_id == root.trace_id

    def test_sibling_traces_are_distinct(self, tr):
        with tr.span("a"):
            pass
        with tr.span("b"):
            pass
        a, b = tr.finished_spans()
        assert a["trace_id"] != b["trace_id"]

    def test_escaping_exception_stamped_as_error_attr(self, tr):
        with pytest.raises(ValueError):
            with tr.span("doomed"):
                raise ValueError("nope")
        (s,) = tr.finished_spans(name="doomed")
        assert s["attrs"]["error"] == "ValueError"

    def test_manual_span_lifetime_and_end_idempotent(self, tr):
        s = tr.start_span("manual", rid=7)
        s.end()
        t1 = s.t1
        s.end()                       # second end must not re-record
        assert s.t1 == t1
        assert len(tr.finished_spans(name="manual")) == 1

    def test_add_span_retroactive_endpoints(self, tr):
        parent = tr.start_span("p")
        tr.add_span("retro", 10.0, 12.5, parent=parent)
        parent.end()
        (s,) = tr.finished_spans(name="retro")
        assert (s["t0"], s["t1"]) == (10.0, 12.5)
        assert s["parent_id"] == parent.span_id

    def test_sampling_zero_disables_and_noops(self):
        t = Tracer(sample=0.0)
        assert not t.enabled
        with t.span("x") as s:
            s.set_attribute("a", 1)   # must not raise
        assert s.context is None
        assert t.finished_spans() == []

    def test_unsampled_root_children_inherit_decision(self):
        t = Tracer(sample=1e-12)      # root draw virtually never samples
        with t.span("root") as root:
            with t.span("child"):
                pass
        assert root.sampled is False
        assert t.finished_spans() == []

    def test_context_header_round_trip(self):
        ctx = SpanContext("ab" * 8, "cd" * 8, True)
        assert SpanContext.from_header(ctx.to_header()) == ctx
        off = SpanContext("ab" * 8, "cd" * 8, False)
        assert SpanContext.from_header(off.to_header()).sampled is False

    def test_ring_is_bounded(self):
        t = Tracer(capacity=8)
        for i in range(50):
            with t.span(f"s{i}"):
                pass
        assert len(t.finished_spans()) == 8

    def test_slowest_traces_ranked_by_root_duration(self, tr):
        fast = tr.start_span("fast")
        fast.t0 = 0.0
        fast.end(end_time=0.1)
        slow = tr.start_span("slow")
        slow.t0 = 0.0
        tr.add_span("slow.child", 0.0, 4.0, parent=slow)
        slow.end(end_time=5.0)
        traces = tr.slowest_traces(1)
        assert traces[0]["root"] == "slow"
        assert traces[0]["seconds"] == pytest.approx(5.0)
        assert {s["name"] for s in traces[0]["spans"]} == \
            {"slow", "slow.child"}


# -------------------------------------------------- recorder integration
class TestRecorderStamping:
    def test_events_under_span_carry_trace_ids(self, tr):
        fr = flight_recorder()
        with tr.span("work") as s:
            fr.record("inner_tick", i=1)
        fr.record("outer_tick", i=2)
        inner = [e for e in fr.snapshot() if e["kind"] == "inner_tick"][-1]
        outer = [e for e in fr.snapshot() if e["kind"] == "outer_tick"][-1]
        assert inner["trace_id"] == s.trace_id
        assert inner["span_id"] == s.span_id
        assert "trace_id" not in outer

    def test_snapshot_does_not_clear(self):
        fr = FlightRecorder(capacity=8)
        for i in range(5):
            fr.record("tick", i=i)
        assert [e["i"] for e in fr.snapshot(2)] == [3, 4]
        assert len(fr) == 5                 # ring untouched
        assert [e["i"] for e in fr.snapshot()] == list(range(5))


# ------------------------------------------------- cross-thread propagation
class TestThreadPropagation:
    def test_device_prefetch_worker_joins_callers_trace(self, tr):
        from paddle_tpu.io import device_prefetch
        with tr.span("train.loop") as outer:
            batches = list(device_prefetch(
                ({"x": np.ones((2, 2), np.float32)} for _ in range(3)),
                depth=1))
        assert len(batches) == 3
        places = tr.finished_spans(name="prefetch.place")
        assert len(places) == 3
        assert all(p["trace_id"] == outer.trace_id for p in places)
        assert all(p["thread"] != outer.thread for p in places)

    def test_dataloader_prefetch_thread_joins_callers_trace(self, tr):
        from paddle_tpu.io.dataloader import DataLoader

        class _DS:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.full((2,), i, np.float32)

        with tr.span("epoch") as outer:
            dl = DataLoader(_DS(), batch_size=4, num_workers=0)
            batches = [b for b in dl]
        assert len(batches) == 2
        spans = tr.finished_spans(name="dataloader.batch")
        assert spans and all(s["trace_id"] == outer.trace_id
                             for s in spans)

    def test_attach_explicit_context_on_plain_thread(self, tr):
        with tr.span("submitter") as outer:
            ctx = tr.current_context()
        seen = {}

        def work():
            with tr.attach(ctx):
                with tr.span("worker.task") as s:
                    seen["trace"] = s.trace_id
        th = threading.Thread(target=work)
        th.start()
        th.join()
        assert seen["trace"] == outer.trace_id
        (s,) = tr.finished_spans(name="worker.task")
        assert s["parent_id"] == outer.span_id


# ------------------------------------------------ serving engine tracing
@pytest.fixture(scope="module")
def tiny_model():
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pp.seed(0)
    cfg = LlamaConfig.tiny(vocab_size=128, hidden_size=32,
                           intermediate_size=64, num_hidden_layers=2,
                           num_attention_heads=2, num_key_value_heads=2,
                           max_position_embeddings=128)
    return LlamaForCausalLM(cfg)


class TestServingTracing:
    def test_request_lifecycle_spans_across_engine_thread(self, tr,
                                                          tiny_model):
        """Requests enqueued on the main thread, engine loop driven on a
        DIFFERENT thread: the request's root span must still own the
        prefill/decode children (context rides the request object)."""
        from paddle_tpu.inference.serving import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(tiny_model, slots=2, max_len=64,
                                       prefill_buckets=(16,))
        rng = np.random.default_rng(0)
        rids = [eng.add_request(rng.integers(0, 128, (5,)),
                                max_new_tokens=3) for _ in range(2)]
        th = threading.Thread(target=eng.run)
        th.start()
        th.join(timeout=120)
        assert not th.is_alive()
        requests = tr.finished_spans(name="serving.request")
        assert len(requests) == 2
        by_trace = {r["trace_id"]: r for r in requests}
        prefills = tr.finished_spans(name="serving.prefill")
        decodes = tr.finished_spans(name="serving.decode_step")
        assert len(prefills) == 2 and decodes
        for child in prefills + decodes:
            root = by_trace[child["trace_id"]]
            assert child["parent_id"] == root["span_id"]
        for r in requests:
            assert r["attrs"]["status"] == "ok"
            assert r["attrs"]["generated"] == 3
        # retirement events are stamped with the request trace ids
        retires = [e for e in flight_recorder().snapshot()
                   if e["kind"] == "serving.retire"
                   and e.get("trace_id") in by_trace]
        assert len(retires) >= 2
        # satellite: retired statuses self-describe their lifecycle
        for rid in rids:
            st = eng.request_status(rid)
            assert st == "ok"
            t = st.timings
            assert 0 < t["queue_s"] <= t["ttft_s"] <= t["total_s"]
            assert t["admitted"] <= t["first_token"] <= t["retired"]
            assert st.trace_id in by_trace

    def test_timeout_status_keeps_partial_timings(self, tr, tiny_model):
        from paddle_tpu.inference.serving import ContinuousBatchingEngine
        eng = ContinuousBatchingEngine(tiny_model, slots=1, max_len=64,
                                       prefill_buckets=(16,))
        rid = eng.add_request(np.arange(5), max_new_tokens=3,
                              timeout_s=-1.0)   # already expired
        eng.run()
        st = eng.request_status(rid)
        assert st == "timeout"
        assert st.timings["enqueued"] > 0
        assert st.timings["admitted"] == 0.0    # never reached a slot
        # canonical schema: every TIMING_KEYS key is present; a phase
        # never reached reads 0.0 (ISSUE 20 timings hardening)
        assert st.timings["queue_s"] == 0.0


# ------------------------------------------------ train step span tree
class TestTrainStepTracing:
    def test_step_children_and_accum_nesting(self, tr, tiny_model):
        from paddle_tpu.jit import TrainStep
        opt = pp.optimizer.SGD(learning_rate=1e-2,
                               parameters=tiny_model.parameters())
        step = TrainStep(tiny_model, opt, accum_steps=2)
        ids = np.zeros((2, 8), np.int32)
        step({"input_ids": ids, "labels": ids})
        spans = {s["span_id"]: s for s in tr.finished_spans()}
        by_name = {s["name"]: s for s in spans.values()}
        root = by_name["train.step"]
        for child in ("train.h2d", "train.dispatch", "train.guard"):
            assert by_name[child]["parent_id"] == root["span_id"]
        accum = by_name["train.accum_microbatches"]
        assert accum["parent_id"] == by_name["train.dispatch"]["span_id"]
        # >= 3 nesting levels: step -> dispatch -> accum

    def test_record_event_nests_under_active_span(self, tr):
        from paddle_tpu import profiler as prof
        with tr.span("outer") as outer:
            with prof.RecordEvent("annotated", event_type="Forward"):
                pass
        (s,) = tr.finished_spans(name="annotated")
        assert s["parent_id"] == outer.span_id
        assert s["attrs"]["cat"] == "Forward"


# ------------------------------------------- cross-host (TCPStore) handoff
class TestStoreHandoff:
    def test_two_worker_store_context_stitches_one_trace(self, tr):
        """Simulated 2-worker handoff: 'worker 0' roots a generation
        span and injects its context into the store; 'worker 1'
        (separate thread + separate client connection) extracts it and
        parents its own work under it — both sides land in ONE trace."""
        from paddle_tpu.distributed.elastic import free_port
        from paddle_tpu.distributed.tcp_store import TCPStore
        from paddle_tpu.observability.tracing import (extract_context,
                                                      inject_context)
        port = free_port()
        try:
            master = TCPStore("127.0.0.1", port, is_master=True)
        except Exception as e:  # pragma: no cover - no native lib
            pytest.skip(f"native TCPStore unavailable: {e}")
        try:
            gen_span = tr.start_span("elastic.generation", generation=0)
            assert inject_context(master, key="trace/gen/0",
                                  ctx=gen_span.context)
            result = {}

            def worker_one():
                client = TCPStore("127.0.0.1", port, is_master=False)
                ctx = extract_context(client, key="trace/gen/0")
                tr.set_process_context(ctx)
                try:
                    with tr.span("worker.step") as s:
                        result["trace"] = s.trace_id
                finally:
                    tr.set_process_context(None)
                    client.close()
            th = threading.Thread(target=worker_one)
            th.start()
            th.join(timeout=30)
            gen_span.end()
            assert result["trace"] == gen_span.trace_id
            (ws,) = tr.finished_spans(name="worker.step")
            assert ws["parent_id"] == gen_span.span_id
            # store ops themselves were spanned (root_eligible=False:
            # none of them may pollute the slowest-trace root table)
            assert tr.finished_spans(name="store.set")
            roots = [t["root"] for t in tr.slowest_traces(10)]
            assert all(not r.startswith("store.") for r in roots)
        finally:
            master.close()

    def test_extract_absent_key_is_none(self, tr):
        class _FakeStore:
            def check(self, key):
                return False

            def get(self, key, wait=True):
                raise KeyError(key)
        from paddle_tpu.observability.tracing import extract_context
        assert extract_context(_FakeStore(), key="trace/none") is None


# ------------------------------------------------------- chrome export
class TestChromeExport:
    def test_export_shape_and_ids(self, tr, tmp_path):
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        out = tmp_path / "trace.json"
        trace = tr.export_chrome(str(out))
        loaded = json.loads(out.read_text())
        assert loaded["traceEvents"] == trace["traceEvents"]
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"outer", "inner"}
        inner = next(e for e in xs if e["name"] == "inner")
        outer = next(e for e in xs if e["name"] == "outer")
        assert inner["args"]["parent_id"] == outer["args"]["span_id"]
        # containment: the child interval nests inside the parent's
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] \
            + 1e-3
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in trace["traceEvents"])


# ------------------------------------------------------------ watchdog
class TestWatchdogRules:
    def _dog(self, reg, rules, **kw):
        kw.setdefault("cooldown", 0.0)
        rec = FlightRecorder(capacity=64)
        return Watchdog(rules=rules, registry=reg, recorder=rec, **kw), rec

    def test_step_time_drift_trips_and_dumps(self, capsys):
        reg = MetricsRegistry()
        h = reg.histogram("paddle_tpu_train_step_seconds")
        for _ in range(10):
            h.observe(0.01)
        wd, rec = self._dog(reg, [StepTimeDriftRule(factor=1.5,
                                                    min_samples=1)])
        assert wd.evaluate_once(now=1.0) == []      # seeds the baseline
        for _ in range(5):
            h.observe(0.1)                          # forced regression
        alerts = wd.evaluate_once(now=2.0)
        assert len(alerts) == 1
        assert "baseline" in alerts[0].detail
        assert reg.get("paddle_tpu_slo_breaches_total").labels(
            rule="step_time_drift").value() == 1
        assert [e for e in rec.snapshot()
                if e["kind"] == "slo_breach"]
        assert '"slo_alert"' in capsys.readouterr().err

    def test_drift_needs_min_samples(self):
        reg = MetricsRegistry()
        h = reg.histogram("paddle_tpu_train_step_seconds")
        for _ in range(3):
            h.observe(0.01)
        wd, _ = self._dog(reg, [StepTimeDriftRule(factor=1.5,
                                                  min_samples=5)])
        wd.evaluate_once(now=1.0)
        for _ in range(3):
            h.observe(1.0)            # huge, but under min_samples
        assert wd.evaluate_once(now=2.0) == []

    def test_recompile_storm(self):
        reg = MetricsRegistry()
        c = reg.counter("paddle_tpu_train_recompiles_total")
        wd, _ = self._dog(reg, [RecompileStormRule(max_delta=2)])
        c.inc(1)
        assert wd.evaluate_once(now=1.0) == []      # seeds
        c.inc(2)
        assert wd.evaluate_once(now=2.0) == []      # at threshold: ok
        c.inc(5)
        alerts = wd.evaluate_once(now=3.0)
        assert len(alerts) == 1 and "recompiles" in alerts[0].detail

    def test_queue_saturation_needs_consecutive_intervals(self):
        reg = MetricsRegistry()
        depth = [0.0]
        reg.gauge("paddle_tpu_serving_queue_depth").set_function(
            lambda: depth[0])
        wd, _ = self._dog(reg, [QueueSaturationRule(threshold=4,
                                                    consecutive=2)])
        depth[0] = 9
        assert wd.evaluate_once(now=1.0) == []      # streak 1
        depth[0] = 2
        assert wd.evaluate_once(now=2.0) == []      # streak reset
        depth[0] = 9
        assert wd.evaluate_once(now=3.0) == []
        alerts = wd.evaluate_once(now=4.0)          # streak 2
        assert len(alerts) == 1

    def test_skip_streak_sums_reason_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("paddle_tpu_train_step_skipped_total",
                        labelnames=("reason",))
        wd, _ = self._dog(reg, [SkipStreakRule(max_delta=3)])
        assert wd.evaluate_once(now=1.0) == []
        c.labels(reason="nonfinite_loss").inc(2)
        c.labels(reason="nonfinite_grad").inc(3)
        alerts = wd.evaluate_once(now=2.0)
        assert len(alerts) == 1 and "skipped" in alerts[0].detail

    def test_heartbeat_gap_arms_only_after_progress(self):
        reg = MetricsRegistry()
        c = reg.counter("paddle_tpu_train_steps_total")
        wd, _ = self._dog(reg, [HeartbeatGapRule(max_gap_s=10)])
        assert wd.evaluate_once(now=0.0) == []      # value 0: unarmed
        assert wd.evaluate_once(now=100.0) == []    # still unarmed
        c.inc(5)
        assert wd.evaluate_once(now=101.0) == []    # progress seen
        assert wd.evaluate_once(now=105.0) == []    # inside the gap
        alerts = wd.evaluate_once(now=120.0)
        assert len(alerts) == 1 and "frozen" in alerts[0].detail
        c.inc()                                      # progress resumes
        assert wd.evaluate_once(now=121.0) == []

    def test_cooldown_suppresses_refires(self):
        reg = MetricsRegistry()
        depth = [99.0]
        reg.gauge("paddle_tpu_serving_queue_depth").set_function(
            lambda: depth[0])
        wd, _ = self._dog(reg, [QueueSaturationRule(threshold=4,
                                                    consecutive=1)],
                          cooldown=60.0)
        assert len(wd.evaluate_once(now=1.0)) == 1
        assert wd.evaluate_once(now=10.0) == []     # inside cooldown
        assert len(wd.evaluate_once(now=100.0)) == 1

    def test_broken_rule_does_not_kill_the_dog(self):
        class _Bad(StepTimeDriftRule):
            def evaluate(self, registry, now):
                raise RuntimeError("scrape exploded")
        reg = MetricsRegistry()
        depth = [99.0]
        reg.gauge("paddle_tpu_serving_queue_depth").set_function(
            lambda: depth[0])
        wd, _ = self._dog(reg, [_Bad(), QueueSaturationRule(
            threshold=4, consecutive=1)])
        assert len(wd.evaluate_once(now=1.0)) == 1  # good rule still ran

    def test_rules_from_spec(self):
        rules = rules_from_spec(
            "step_time_drift:factor=2.5,min_samples=10;"
            "queue_saturation:threshold=64;heartbeat_gap")
        assert [type(r).__name__ for r in rules] == \
            ["StepTimeDriftRule", "QueueSaturationRule",
             "HeartbeatGapRule"]
        assert rules[0].factor == 2.5 and rules[0].min_samples == 10
        assert rules[1].threshold == 64
        with pytest.raises(ValueError, match="unknown SLO rule"):
            rules_from_spec("no_such_rule:x=1")

    def test_slowest_traces_dumped_on_breach(self, capsys):
        t = Tracer(sample=1.0)
        with t.span("slow.root"):
            pass
        reg = MetricsRegistry()
        depth = [99.0]
        reg.gauge("paddle_tpu_serving_queue_depth").set_function(
            lambda: depth[0])
        wd, _ = self._dog(reg, [QueueSaturationRule(threshold=4,
                                                    consecutive=1)],
                          trace_source=t)
        assert len(wd.evaluate_once(now=1.0)) == 1
        err = capsys.readouterr().err
        assert '"slow_traces"' in err and "slow.root" in err


# --------------------------------------- exposition satellite (buckets)
class TestPrometheusBuckets:
    def test_histogram_quantile_math_works_from_exposition(self):
        """PromQL histogram_quantile needs cumulative le-buckets + +Inf;
        re-derive p90 from the rendered TEXT and check it brackets the
        true quantile — the Grafana path, end to end."""
        reg = MetricsRegistry()
        h = reg.histogram("paddle_tpu_q_seconds", "q",
                          buckets=(0.01, 0.05, 0.1, 0.5))
        for v in [0.02] * 80 + [0.3] * 20:
            h.observe(v)
        text = render_prometheus(reg)
        buckets = {}
        for line in text.splitlines():
            if line.startswith("paddle_tpu_q_seconds_bucket"):
                le = line.split('le="')[1].split('"')[0]
                buckets[le] = float(line.rsplit(" ", 1)[1])
        bounds = [k for k in buckets if k != "+Inf"]
        # cumulative and capped by +Inf == count
        cums = [buckets[b] for b in bounds]
        assert cums == sorted(cums)
        assert buckets["+Inf"] == 100
        assert "paddle_tpu_q_seconds_count 100" in text
        # histogram_quantile(0.9): rank 90 falls in the (0.1, 0.5] bucket
        target = 0.9 * buckets["+Inf"]
        prev_b, prev_c = 0.0, 0.0
        for b in bounds:
            if buckets[b] >= target:
                width = float(b) - prev_b
                est = prev_b + width * (target - prev_c) \
                    / (buckets[b] - prev_c)
                break
            prev_b, prev_c = float(b), buckets[b]
        assert 0.1 < est <= 0.5

    def test_histogram_quantile_from_federated_exposition(self):
        """ISSUE 11 satellite: the SAME histogram_quantile math over
        the FEDERATED (3-host, bucket-summed) exposition must match the
        estimate from one histogram that observed the pooled raw
        stream — federation must not bend quantiles."""
        from paddle_tpu.observability.fleet import (FleetAggregator,
                                                    LocalStore,
                                                    MetricsPublisher)
        bounds = (0.01, 0.05, 0.1, 0.5)
        per_host = ([0.02] * 30 + [0.3] * 5, [0.02] * 30 + [0.3] * 10,
                    [0.02] * 20 + [0.3] * 5)
        store = LocalStore()
        pooled = []
        for i, obs in enumerate(per_host):
            reg = MetricsRegistry()
            h = reg.histogram("paddle_tpu_q_seconds", "q",
                              buckets=bounds)
            for v in obs:
                h.observe(v)
            pooled.extend(obs)
            MetricsPublisher(store, registry=reg, host=f"h{i}",
                             interval=999, publish_goodput=False,
                             publish_traces=False).publish_once()
        agg = FleetAggregator(store=store)

        def quantile_from_text(text, q):
            buckets = {}
            for line in text.splitlines():
                if line.startswith("paddle_tpu_q_seconds_bucket"):
                    le = line.split('le="')[1].split('"')[0]
                    buckets[le] = float(line.rsplit(" ", 1)[1])
            target = q * buckets["+Inf"]
            prev_b, prev_c = 0.0, 0.0
            for b in [k for k in buckets if k != "+Inf"]:
                if buckets[b] >= target:
                    return prev_b + (float(b) - prev_b) * \
                        (target - prev_c) / (buckets[b] - prev_c)
                prev_b, prev_c = float(b), buckets[b]
            return float(b)

        fed_text = render_prometheus(agg)
        ref = MetricsRegistry()
        rh = ref.histogram("paddle_tpu_q_seconds", "q", buckets=bounds)
        for v in pooled:
            rh.observe(v)
        ref_text = render_prometheus(ref)
        assert f"paddle_tpu_q_seconds_count {len(pooled)}" in fed_text
        for q in (0.5, 0.9, 0.99):
            assert abs(quantile_from_text(fed_text, q)
                       - quantile_from_text(ref_text, q)) < 1e-12, q

    def test_jsonl_payload_keeps_quantile_summaries(self):
        from paddle_tpu.observability import render_json
        reg = MetricsRegistry()
        h = reg.histogram("paddle_tpu_q2_seconds")
        for _ in range(10):
            h.observe(0.02)
        payload = json.loads(render_json(reg))
        (fam,) = [m for m in payload["metrics"]
                  if m["name"] == "paddle_tpu_q2_seconds"]
        summary = fam["series"][0]["summary"]
        assert summary["count"] == 10
        assert {"p50", "p90", "p99"} <= set(summary)
