"""Memory-bound-tail package tests (ISSUE 3).

Covers: the Pallas vocab-blockwise fused cross-entropy (forward + grad
parity vs the reference path, ignore_index, the no-[B,S,V]-fp32
jaxpr/cost-model assertion), the flash-attention backward vs jax.grad of
naive attention, TrainStep microbatch gradient accumulation equivalence,
the device-prefetch iterator, DataLoader prefetch lifecycle, and the
soft-label + weight mean-reduction fix.

Everything runs interpret-mode on CPU (conftest pins JAX_PLATFORMS).
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.core.dispatch import unwrap  # noqa: E402


# ---------------------------------------------------------------------------
# fused cross-entropy
# ---------------------------------------------------------------------------

class TestFusedCrossEntropyKernel:
    def test_fwd_matches_logsumexp(self):
        from paddle_tpu.ops.pallas.cross_entropy import \
            fused_softmax_cross_entropy
        rng = np.random.default_rng(0)
        for t, v in [(64, 256), (100, 384), (8, 128)]:
            x = jnp.asarray(rng.standard_normal((t, v)) * 3, jnp.float32)
            lbl = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)
            got = fused_softmax_cross_entropy(x, lbl)
            ref = jax.nn.logsumexp(x, axis=-1) - \
                jnp.take_along_axis(x, lbl[:, None], 1)[:, 0]
            assert float(jnp.abs(got - ref).max()) < 1e-5

    def test_grad_matches_softmax_minus_onehot(self):
        from paddle_tpu.ops.pallas.cross_entropy import \
            fused_softmax_cross_entropy
        rng = np.random.default_rng(1)
        t, v = 48, 256
        x = jnp.asarray(rng.standard_normal((t, v)), jnp.float32)
        lbl = jnp.asarray(rng.integers(0, v, (t,)), jnp.int32)
        # weighted sum exercises per-token cotangents
        w = jnp.asarray(rng.standard_normal((t,)), jnp.float32)
        g = jax.grad(lambda a: jnp.sum(
            fused_softmax_cross_entropy(a, lbl) * w))(x)
        p = jax.nn.softmax(x, axis=-1)
        onehot = jax.nn.one_hot(lbl, v)
        ref = (p - onehot) * w[:, None]
        assert float(jnp.abs(g - ref).max()) < 1e-5

    def test_vocab_not_multiple_of_128_rejected(self):
        from paddle_tpu.ops.pallas.cross_entropy import (
            fused_ce_eligible, fused_softmax_cross_entropy)
        assert not fused_ce_eligible(8, 200)
        with pytest.raises(ValueError):
            fused_softmax_cross_entropy(jnp.zeros((8, 200)),
                                        jnp.zeros((8,), jnp.int32))


class TestFusedCrossEntropyRouting:
    @pytest.fixture(autouse=True)
    def _force_fused(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "1")

    def _ref(self, monkeypatch, *args, **kw):
        import paddle_tpu.nn.functional as F
        monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "0")
        try:
            return unwrap(F.cross_entropy(*args, **kw))
        finally:
            monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "1")

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_parity_with_ignore_index(self, monkeypatch, reduction):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(2)
        B, S, V = 2, 24, 256
        x = jnp.asarray(rng.standard_normal((B, S, V)), jnp.float32)
        lbl = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        lbl = lbl.at[0, :7].set(-100)
        got = unwrap(F.cross_entropy(x, lbl, reduction=reduction))
        ref = self._ref(monkeypatch, x, lbl, reduction=reduction)
        err = float(jnp.abs(jnp.asarray(got) - jnp.asarray(ref)).max())
        assert err < 1e-5, err

    def test_grad_parity_bf16(self, monkeypatch):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(3)
        B, S, V = 2, 16, 256
        x = jnp.asarray(rng.standard_normal((B, S, V)), jnp.bfloat16)
        lbl = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)
        lbl = lbl.at[1, -3:].set(-100)

        def loss(a):
            return unwrap(F.cross_entropy(a, lbl))

        g1 = jax.grad(loss)(x)
        monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "0")
        g0 = jax.grad(loss)(x)
        err = float(jnp.abs((g1 - g0).astype(jnp.float32)).max())
        assert err < 1e-4, err
        # ignored rows contribute no gradient
        assert float(jnp.abs(g1.astype(jnp.float32)[1, -3:]).max()) == 0.0

    def test_no_fp32_vocab_intermediate_in_grad_jaxpr(self):
        """Acceptance: with bf16 logits the fused path's fwd+bwd jaxpr
        holds NO fp32 [B*S, V]-sized value outside the Pallas kernels —
        the fp32 log-softmax (and the one-hot) never materialize."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.analysis.tracing import walk_eqns
        B, S, V = 2, 64, 512
        x = jnp.zeros((B, S, V), jnp.bfloat16)
        lbl = jnp.zeros((B, S), jnp.int32)

        jaxpr = jax.make_jaxpr(
            jax.grad(lambda a: unwrap(F.cross_entropy(a, lbl))))(x)
        big_fp32 = []
        for eqn, path, _w in walk_eqns(jaxpr):
            if "pallas_call[" in path:
                continue  # kernel-internal avals are block-shaped anyway
            for ovar in eqn.outvars:
                av = getattr(ovar, "aval", None)
                if av is not None and av.dtype == jnp.float32 and \
                        int(np.prod(av.shape)) >= B * S * V:
                    big_fp32.append((eqn.primitive.name, av.shape))
        assert not big_fp32, big_fp32

    def test_cost_model_charges_fused_traffic(self, monkeypatch):
        """The analysis cost model accounts a pallas_call at CALL level:
        the fused CE moves strictly fewer (unfused-model) bytes than the
        reference lowering of the same loss+grad."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.analysis import check
        B, S, V = 2, 64, 512
        x = jnp.zeros((B, S, V), jnp.bfloat16)
        lbl = jnp.zeros((B, S), jnp.int32)

        def loss(a, b):
            return unwrap(F.cross_entropy(a, b))

        def cost():
            rep = check(jax.grad(loss), x, lbl, passes=["cost-model"])
            return rep.extras["cost"]

        fused = cost()
        monkeypatch.setenv("PADDLE_TPU_FUSED_CE", "0")
        fallback = cost()
        assert fused.total_bytes < 0.5 * fallback.total_bytes, \
            (fused.total_bytes, fallback.total_bytes)

    def test_route_counter_increments(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.observability import default_registry
        x = jnp.zeros((4, 256), jnp.float32)
        lbl = jnp.zeros((4,), jnp.int32)
        unwrap(F.cross_entropy(x, lbl))
        m = default_registry().get("paddle_tpu_fused_ce_calls_total")
        got = {"/".join(k): c.value() for k, c in m.series()}
        assert got.get("fused", 0) >= 1


# ---------------------------------------------------------------------------
# flash-attention backward
# ---------------------------------------------------------------------------

class TestFlashBackwardVsNaive:
    @pytest.mark.parametrize("pallas_bwd", [True, False])
    @pytest.mark.parametrize("causal", [True, False])
    def test_grads_match_naive_attention(self, pallas_bwd, causal):
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        from paddle_tpu.nn.functional.attention import _sdpa_reference
        rng = np.random.default_rng(4)
        b, s, h, hk, d = 1, 256, 4, 2, 128
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)

        def loss_flash(*a):
            return (flash_attention(*a, causal=causal,
                                    pallas_bwd=pallas_bwd)
                    .astype(jnp.float32) ** 2).mean()

        def loss_ref(*a):
            return (unwrap(_sdpa_reference(*a, is_causal=causal))
                    .astype(jnp.float32) ** 2).mean()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gr):
            assert float(jnp.abs(a - b_).max()) < 1e-4

    def test_flash_bwd_env_knob(self, monkeypatch):
        from paddle_tpu.ops.pallas.flash_attention import flash_bwd_env
        monkeypatch.delenv("PADDLE_TPU_FLASH_BWD", raising=False)
        monkeypatch.delenv("PT_FLASH_PALLAS_BWD", raising=False)
        assert flash_bwd_env() is None
        monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "1")
        assert flash_bwd_env() is True
        monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "0")
        assert flash_bwd_env() is False
        monkeypatch.delenv("PADDLE_TPU_FLASH_BWD")
        monkeypatch.setenv("PT_FLASH_PALLAS_BWD", "yes")  # legacy alias
        assert flash_bwd_env() is True


# ---------------------------------------------------------------------------
# microbatch gradient accumulation
# ---------------------------------------------------------------------------

class TestGradAccum:
    def _train(self, accum, steps=3, lr=1e-3):
        import paddle_tpu as pp
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        pp.seed(0)
        cfg = LlamaConfig.tiny()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (4, 17)).astype(np.int32)
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
        model = LlamaForCausalLM(cfg)
        opt = pp.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
        step = TrainStep(model, opt, accum_steps=accum)
        losses = [float(step(batch)) for _ in range(steps)]
        return losses, step.params

    @pytest.mark.slow
    def test_accum4_matches_full_batch(self):
        l1, p1 = self._train(1)
        l4, p4 = self._train(4)
        for a, b in zip(l1, l4):
            assert abs(a - b) < 1e-4, (l1, l4)
        for n in p1:
            d = float(jnp.abs(p1[n].astype(jnp.float32)
                              - p4[n].astype(jnp.float32)).max())
            assert d < 1e-4, (n, d)

    def test_indivisible_batch_raises(self):
        with pytest.raises(ValueError, match="not divisible"):
            self._train(3, steps=1)

    def test_accum_histogram_observed(self):
        from paddle_tpu.observability import default_registry
        self._train(2, steps=1)
        m = default_registry().get("paddle_tpu_train_accum_microbatches")
        assert m is not None and m.series()

    def test_invalid_accum_steps(self):
        import paddle_tpu as pp
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        model = LlamaForCausalLM(LlamaConfig.tiny())
        opt = pp.optimizer.SGD(learning_rate=1e-2,
                               parameters=model.parameters())
        with pytest.raises(ValueError, match="accum_steps"):
            TrainStep(model, opt, accum_steps=0)


# ---------------------------------------------------------------------------
# device prefetch
# ---------------------------------------------------------------------------

class TestDevicePrefetch:
    def test_order_values_and_device_residency(self):
        from paddle_tpu.io import device_prefetch

        def gen():
            for i in range(10):
                yield {"x": np.full((2, 2), i, np.float32)}

        with device_prefetch(gen(), depth=2) as it:
            got = list(it)
        assert len(got) == 10
        assert all(isinstance(b["x"], jax.Array) for b in got)
        assert [float(b["x"][0, 0]) for b in got] == list(range(10))

    def test_early_close_stops_thread(self):
        from paddle_tpu.io import device_prefetch

        def gen():
            for i in range(1000):
                yield np.zeros((4,), np.float32)

        it = device_prefetch(gen(), depth=2)
        next(it)
        it.close()
        deadline = time.time() + 5
        while it._thread.is_alive() and time.time() < deadline:
            time.sleep(0.01)
        assert not it._thread.is_alive(), "prefetch thread leaked"

    def test_exception_propagates(self):
        from paddle_tpu.io import device_prefetch

        def bad():
            yield np.zeros((2,), np.float32)
            raise RuntimeError("boom")

        it = device_prefetch(bad())
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            while True:
                next(it)

    def test_sharded_placement_with_mesh(self):
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.io import device_prefetch
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs the virtual multi-device CPU mesh")
        mesh = jax.sharding.Mesh(np.array(devs[:2]), ("dp",))

        def gen():
            yield np.arange(8, dtype=np.float32).reshape(2, 4)

        with device_prefetch(gen(), mesh=mesh, spec=P("dp")) as it:
            out = next(it)
        assert len(out.sharding.device_set) == 2
        np.testing.assert_array_equal(
            np.asarray(out), np.arange(8, dtype=np.float32).reshape(2, 4))

    def test_prefetch_metrics_exist(self):
        from paddle_tpu.io import device_prefetch
        from paddle_tpu.observability import default_registry
        with device_prefetch(iter([np.zeros(2)]), depth=1) as it:
            list(it)
        assert default_registry().get(
            "paddle_tpu_prefetch_batches_total").value() >= 1


# ---------------------------------------------------------------------------
# DataLoader prefetch lifecycle (satellite fix)
# ---------------------------------------------------------------------------

class TestDataLoaderAbandonment:
    def test_early_break_then_close_leaves_no_thread(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        ds = TensorDataset([np.arange(400, dtype=np.float32)
                            .reshape(100, 4)])
        dl = DataLoader(ds, batch_size=5, use_buffer_reader=True,
                        prefetch_factor=2)
        it = iter(dl)
        next(it)  # consume one batch, abandon the rest
        it.close()
        deadline = time.time() + 5
        while it._thread.is_alive() and time.time() < deadline:
            time.sleep(0.01)
        assert not it._thread.is_alive(), "dataloader prefetch thread leaked"

    def test_context_manager_and_reuse(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        ds = TensorDataset([np.arange(40, dtype=np.float32).reshape(10, 4)])
        dl = DataLoader(ds, batch_size=2, use_buffer_reader=True)
        with iter(dl) as it:
            next(it)
        # a fresh epoch works after closing the previous iterator
        assert sum(1 for _ in dl) == 5

    def test_close_idempotent(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        ds = TensorDataset([np.zeros((4, 2), np.float32)])
        it = iter(DataLoader(ds, batch_size=2, use_buffer_reader=True))
        list(it)
        it.close()
        it.close()


# ---------------------------------------------------------------------------
# soft-label + weight mean reduction (satellite fix)
# ---------------------------------------------------------------------------

class TestSoftLabelWeightMean:
    def test_divides_by_weight_sum(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(5)
        n, c = 6, 5
        x = jnp.asarray(rng.standard_normal((n, c)), jnp.float32)
        lbl = jnp.asarray(rng.integers(0, c, (n,)), jnp.int32)
        soft = jax.nn.one_hot(lbl, c)
        w = jnp.asarray(rng.uniform(0.5, 2.0, (c,)), jnp.float32)
        got = float(unwrap(F.cross_entropy(x, soft, weight=w,
                                           soft_label=True)))
        # reference math: weighted per-row CE, normalized by sum of
        # per-row weights — identical to the hard-label weighted branch
        logp = jax.nn.log_softmax(x, axis=-1)
        per = -jnp.take_along_axis(logp, lbl[:, None], 1)[:, 0]
        wr = jnp.take(w, lbl)
        want = float(jnp.sum(per * wr) / jnp.sum(wr))
        assert abs(got - want) < 1e-5
        # and matches the hard-label branch exactly
        hard = float(unwrap(F.cross_entropy(x, lbl, weight=w)))
        assert abs(got - hard) < 1e-5

    def test_unweighted_soft_label_unchanged(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.standard_normal((4, 3)), jnp.float32)
        soft = jax.nn.softmax(jnp.asarray(
            rng.standard_normal((4, 3)), jnp.float32))
        got = float(unwrap(F.cross_entropy(x, soft, soft_label=True)))
        logp = jax.nn.log_softmax(x, axis=-1)
        want = float(jnp.mean(-jnp.sum(soft * logp, axis=-1)))
        assert abs(got - want) < 1e-5
