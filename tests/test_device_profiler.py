"""Device-time profiler + roofline-gap attribution (ISSUE 6 tentpole).

CPU-safe coverage of the whole layer: AOT compile observability
(lower/compile spans, per-target counters, executable cost/memory
introspection), the portable segment-timing fallback, the attribution
join against the PR-1 cost model, the HBM census/watermark monitor with
leak detection, the TrainStep/serving AOT integration, the new watchdog
rules, and the bench --compare helper.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.observability.device_profiler import (
    AttributionResult, DeviceMemoryMonitor, DeviceProfiler, Segment,
    aot_compile, compile_records, compiled_stats, detect_roofline,
    device_memory_monitor, llama_step_segments, signature_of)
from paddle_tpu.observability.metrics import MetricsRegistry, \
    default_registry
from paddle_tpu.observability.tracing import tracer


# ---------------------------------------------------------------- aot compile
class TestAotCompile:
    def test_compiled_matches_jit(self):
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        compiled, info = aot_compile(lambda a, b: a @ b, x, x,
                                     target="test.matmul")
        np.testing.assert_allclose(np.asarray(compiled(x, x)),
                                   np.asarray(x @ x), rtol=1e-6)
        assert info.lower_s >= 0 and info.compile_s >= 0
        assert info.target == "test.matmul"

    def test_cost_and_memory_analysis(self):
        x = jnp.ones((32, 32), jnp.float32)
        _, info = aot_compile(lambda a, b: jnp.tanh(a @ b), x, x,
                              target="test.cost")
        st = info.stats
        # 2*M*N*K matmul flops must be visible to XLA's own counter
        assert st.flops >= 2 * 32 * 32 * 32
        assert st.bytes_accessed > 0
        assert st.argument_bytes == 2 * 32 * 32 * 4
        assert st.peak_bytes >= st.argument_bytes

    def test_compile_counter_and_spans(self):
        x = jnp.ones((4, 4))
        aot_compile(lambda a: a + 1, x, target="test.counted")
        c = default_registry().get("paddle_tpu_compile_total")
        series = {"/".join(k): ch.value() for k, ch in c.series()}
        assert series.get("test.counted", 0) >= 1
        names = {s["name"] for s in tracer().finished_spans()}
        assert {"compile", "compile.lower", "compile.xla"} <= names

    def test_compile_records_carry_signature(self):
        x = jnp.ones((3, 5))
        aot_compile(lambda a: a * 2, x, target="test.sig")
        recs = compile_records(target="test.sig")
        assert recs and "float32[3, 5]" in recs[-1].signature

    def test_no_silent_retrace(self):
        """The AOT executable raises on a novel shape instead of
        recompiling — the serving-tier contract."""
        x = jnp.ones((4, 4))
        compiled, _ = aot_compile(lambda a: a.sum(), x, target="test.fixed")
        with pytest.raises(Exception):
            compiled(jnp.ones((8, 8)))

    def test_compiled_stats_defensive(self):
        class Broken:
            def cost_analysis(self):
                raise RuntimeError("nope")

            def memory_analysis(self):
                raise RuntimeError("nope")
        st = compiled_stats(Broken())
        assert st.flops == 0 and st.peak_bytes == 0


class TestSignature:
    def test_stable_and_shape_sensitive(self):
        a = {"x": jnp.ones((2, 3)), "y": jnp.zeros((4,), jnp.int32)}
        b = {"x": jnp.full((2, 3), 7.0), "y": jnp.ones((4,), jnp.int32)}
        assert signature_of(a) == signature_of(b)  # values don't matter
        c = {"x": jnp.ones((2, 4)), "y": jnp.zeros((4,), jnp.int32)}
        assert signature_of(a) != signature_of(c)

    def test_treedef_sensitive(self):
        assert signature_of({"x": jnp.ones(2)}) != \
            signature_of([jnp.ones(2)])


def test_detect_roofline_env_override(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "123e12")
    monkeypatch.setenv("PADDLE_TPU_HBM_BW", "456e9")
    peak, bw = detect_roofline()
    assert peak == 123e12 and bw == 456e9


# ------------------------------------------------------------ segment timing
class TestDeviceProfiler:
    def test_fallback_timer_ranks_segments(self):
        prof = DeviceProfiler()
        small = jnp.ones((16, 16), jnp.float32)
        big = jnp.ones((256, 256), jnp.float32)
        prof.add_segment("small_mm", lambda a: a @ a, small)
        prof.add_segment("big_mm", lambda a: a @ a, big)
        res = prof.profile(reps=3, warmup=1, parent_span="test.profile")
        by_name = {r.name: r for r in res.segments}
        assert set(by_name) == {"small_mm", "big_mm"}
        assert all(r.device_s > 0 for r in res.segments)
        assert by_name["big_mm"].device_s > by_name["small_mm"].device_s

    def test_attribution_join(self):
        prof = DeviceProfiler()
        x = jnp.ones((64, 64), jnp.float32)
        prof.add_segment("mm", lambda a: a @ a, x)
        res = prof.profile(reps=2, warmup=1, parent_span="test.join")
        (r,) = res.segments
        # predicted roofline comes from the PR-1 cost model with THIS
        # profiler's peaks, and the gap is the measured/predicted join
        assert r.predicted_s > 0
        assert r.model_flops >= 2 * 64 * 64 * 64
        assert r.gap == pytest.approx(r.device_s / r.predicted_s)
        assert r.bound in ("compute", "memory")
        assert r.flops > 0          # XLA side of the join

    def test_table_renders_ranked(self):
        seg = [
            _report("worst", gap=9.0), _report("mid", gap=5.0),
            _report("best", gap=1.1),
        ]
        res = AttributionResult(segments=seg, peak_flops=1e12, hbm_bw=1e11)
        txt = res.table()
        assert "roofline-gap attribution" in txt
        assert txt.index("worst") < txt.index("mid") < txt.index("best")
        rows = res.to_dicts(top=2)
        assert [r["name"] for r in rows] == ["worst", "mid"]
        assert rows[0]["device_ms"] > 0 and rows[0]["predicted_ms"] > 0

    def test_untraceable_segment_skipped(self):
        prof = DeviceProfiler()
        prof.add(Segment("bad", lambda: (_ for _ in ()).throw(
            RuntimeError("boom")), ()))
        prof.add_segment("good", lambda a: a + 1, jnp.ones(4))
        res = prof.profile(reps=1, warmup=0, parent_span="test.skip")
        assert [r.name for r in res.segments] == ["good"]

    def test_segment_histogram_observed(self):
        prof = DeviceProfiler()
        prof.add_segment("histo_seg", lambda a: a * 2, jnp.ones(8))
        prof.profile(reps=1, warmup=0, parent_span="test.histo")
        h = default_registry().get("paddle_tpu_device_segment_seconds")
        series = {"/".join(k): ch for k, ch in h.series()}
        assert series["histo_seg"].count() >= 1


def _report(name, gap):
    from paddle_tpu.observability.device_profiler import SegmentReport
    return SegmentReport(name=name, count=1, group="op",
                         device_s=gap * 1e-4, compile_s=0.0, flops=1.0,
                         bytes_accessed=1.0, peak_bytes=1,
                         model_flops=1.0, model_bytes=1.0,
                         predicted_s=1e-4, gap=gap, bound="memory")


# ------------------------------------------------------- llama decomposition
@pytest.fixture(scope="module")
def tiny_llama():
    import paddle_tpu as pp
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    pp.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    ids = np.random.default_rng(0).integers(
        0, 256, (2, 16)).astype(np.int32)
    return model, {"input_ids": ids, "labels": ids}


class TestLlamaSegments:
    def test_op_groups(self, tiny_llama):
        model, batch = tiny_llama
        segs = llama_step_segments(model, batch)
        names = {s.name for s in segs}
        assert {"embed", "rmsnorm", "attention", "mlp",
                "lm_head_ce"} <= names
        assert len(segs) >= 5
        by_name = {s.name: s for s in segs}
        # counts reflect the model's composition (L=2 for tiny)
        assert by_name["attention"].count == 2
        assert by_name["rmsnorm"].count == 5       # 2 per block + final

    def test_no_grad_variant(self, tiny_llama):
        model, batch = tiny_llama
        segs = llama_step_segments(model, batch, grad=False)
        assert not any("fwdbwd" in s.name for s in segs)
        assert len(segs) >= 5

    def test_rejects_non_llama(self):
        llama_like = object()
        with pytest.raises(ValueError):
            llama_step_segments(llama_like, {})

    def test_profile_and_trace_nesting(self, tiny_llama, tmp_path):
        model, batch = tiny_llama
        prof = DeviceProfiler()
        for seg in llama_step_segments(model, batch, grad=False):
            prof.add(seg)
        res = prof.profile(reps=1, warmup=1, parent_span="train.step")
        assert len(res.ranked()) >= 5
        assert all(r.device_s > 0 and r.predicted_s > 0 and r.gap > 0
                   for r in res.segments)
        trace = tracer().export_chrome(str(tmp_path / "trace.json"))
        spans = {e["args"]["span_id"]: e for e in trace["traceEvents"]
                 if e.get("ph") == "X" and e.get("args", {}).get("span_id")}

        def ancestors(e):
            out, p = [], e["args"].get("parent_id")
            while p and p in spans:
                out.append(spans[p]["name"])
                p = spans[p]["args"].get("parent_id")
            return out
        dev = [e for e in spans.values()
               if e["name"].startswith("device.")]
        assert dev, "no device segments exported"
        assert any("train.step" in ancestors(e) for e in dev)


def test_profiler_summary_device_section(capsys):
    from paddle_tpu.profiler import Profiler
    res = AttributionResult(segments=[_report("seg_a", 3.0)],
                            peak_flops=1e12, hbm_bw=1e11)
    prof = Profiler(timer_only=True)
    prof.start()
    prof.stop()
    prof.add_device_profile(res)
    table = prof.summary()
    assert "roofline-gap attribution" in table
    assert "seg_a" in table


# --------------------------------------------------------------- HBM census
class TestMemoryMonitor:
    def test_sample_and_watermark(self):
        reg = MetricsRegistry()
        mon = DeviceMemoryMonitor(registry=reg)
        keep = jnp.ones((128, 128), jnp.float32)   # keep a buffer live
        v = mon.sample()
        assert v > 0
        assert reg.get("paddle_tpu_device_live_bytes").value() == v
        assert mon.watermark >= v
        mon.sample(live_bytes=v // 2)
        assert mon.watermark >= v                  # watermark is monotone
        del keep

    def test_census_groups_by_shape(self):
        keep = [jnp.ones((33, 7), jnp.float32) for _ in range(3)]
        jax.block_until_ready(keep)
        rows = DeviceMemoryMonitor.census(top=50)
        match = [r for r in rows
                 if r["shape"] == [33, 7] and r["dtype"] == "float32"]
        assert match and match[0]["count"] >= 3
        assert match[0]["bytes"] >= 3 * 33 * 7 * 4
        del keep

    def test_leak_detection_fires_on_monotone_growth(self):
        reg = MetricsRegistry()
        mon = DeviceMemoryMonitor(registry=reg, leak_window=4,
                                  leak_min_bytes=100)
        for b in (1000, 1200, 1400, 1700):
            mon.sample(live_bytes=b)
        assert reg.get(
            "paddle_tpu_device_memory_leak_total").value() == 1
        # window cleared after firing: no immediate re-fire
        mon.sample(live_bytes=1800)
        assert reg.get(
            "paddle_tpu_device_memory_leak_total").value() == 1

    def test_leak_detector_quiet_on_stable(self):
        reg = MetricsRegistry()
        mon = DeviceMemoryMonitor(registry=reg, leak_window=4,
                                  leak_min_bytes=100)
        for b in (1000, 1200, 1100, 1300, 1250, 1400):
            mon.sample(live_bytes=b)
        assert reg.get(
            "paddle_tpu_device_memory_leak_total").value() == 0

    def test_process_monitor_singleton(self):
        assert device_memory_monitor() is device_memory_monitor()


# -------------------------------------------------------- TrainStep AOT path
class TestTrainStepAot:
    @pytest.fixture(scope="class")
    def compiled_step(self, tiny_llama):
        import paddle_tpu as pp
        from paddle_tpu.jit import TrainStep
        model, batch = tiny_llama
        opt = pp.optimizer.SGD(learning_rate=1e-2,
                               parameters=model.parameters())
        step = TrainStep(model, opt)
        info = step.compile(batch)
        return step, batch, info

    def test_compile_info_and_executable_gauges(self, compiled_step):
        step, batch, info = compiled_step
        assert info.stats.flops > 0
        assert info.stats.peak_bytes > 0
        g = default_registry().get("paddle_tpu_xla_flops")
        series = {"/".join(k) for k, _ in g.series()}
        assert any("TrainStep" in s for s in series)

    def test_dispatches_through_compiled(self, compiled_step):
        step, batch, _ = compiled_step
        placed = step._place_batch(batch)
        assert step._dispatch_fn(placed, step._key) is step._compiled
        l0 = float(step(batch))
        l1 = float(step(batch))
        assert np.isfinite(l0) and np.isfinite(l1) and l1 < l0

    def test_mfu_gauge_armed(self, compiled_step):
        step, batch, _ = compiled_step
        step(batch)
        g = default_registry().get("paddle_tpu_train_mfu")
        assert g is not None and g.value() > 0

    def test_novel_shape_falls_back_to_jit(self, compiled_step):
        step, batch, _ = compiled_step
        short = {k: v[:, :8] for k, v in batch.items()}
        loss = float(step(short))          # must not raise
        assert np.isfinite(loss)

    def test_train_compile_span(self, compiled_step):
        names = {s["name"] for s in tracer().finished_spans()}
        assert "train.compile" in names

    def test_watermark_sampled_during_steps(self, compiled_step):
        step, batch, _ = compiled_step
        step(batch)
        g = default_registry().get("paddle_tpu_device_live_bytes")
        assert g is not None and g.value() > 0


# ---------------------------------------------------------- serving AOT path
def test_serving_aot_warmup(tiny_llama):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    model, _ = tiny_llama
    rng = np.random.default_rng(0)
    with ContinuousBatchingEngine(model, slots=2, max_len=64,
                                  prefill_buckets=(16,)) as eng:
        stats = eng.aot_warmup()
        assert set(stats) == {"serving.decode", "serving.insert",
                              "serving.prefill[16]"}
        assert stats["serving.decode"].flops > 0
        assert eng._decode_compiled is not None
        assert 16 in eng._prefill_compiled
        rids = [eng.add_request(rng.integers(0, 256, (5,)),
                                max_new_tokens=4) for _ in range(3)]
        results = eng.run()
        assert len(results) == 3
        assert all(len(toks) >= 1 for _, toks in results.values())
        assert all(eng.request_status(r) == "ok" for r in rids)
    c = default_registry().get("paddle_tpu_compile_total")
    series = {"/".join(k): ch.value() for k, ch in c.series()}
    assert series.get("serving.decode", 0) >= 1


# ------------------------------------------------------------ watchdog rules
class TestNewWatchdogRules:
    def test_mfu_drift_breaches_on_drop(self):
        from paddle_tpu.observability.watchdog import MfuDriftRule
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_train_mfu", "")
        rule = MfuDriftRule(factor=0.8)
        assert rule.evaluate(reg, 0.0) is None     # gauge at 0: unarmed
        g.set(0.50)
        assert rule.evaluate(reg, 1.0) is None     # seeds baseline
        g.set(0.48)
        assert rule.evaluate(reg, 2.0) is None     # within factor
        g.set(0.20)
        detail = rule.evaluate(reg, 3.0)
        assert detail and "MFU" in detail

    def test_mfu_drift_ema_tracks_slow_change(self):
        from paddle_tpu.observability.watchdog import MfuDriftRule
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_train_mfu", "")
        rule = MfuDriftRule(factor=0.8, alpha=0.5)
        for v in (0.50, 0.47, 0.44, 0.42, 0.40):
            g.set(v)
            assert rule.evaluate(reg, 0.0) is None  # gradual: no breach

    def test_compile_storm_breaches_on_churn(self):
        from paddle_tpu.observability.watchdog import CompileStormRule
        reg = MetricsRegistry()
        c = reg.counter("paddle_tpu_compile_total", "",
                        labelnames=("target",))
        rule = CompileStormRule(max_delta=3)
        assert rule.evaluate(reg, 0.0) is None     # seeds
        c.labels(target="a").inc(2)
        assert rule.evaluate(reg, 1.0) is None     # 2 <= 3
        c.labels(target="b").inc(5)
        detail = rule.evaluate(reg, 2.0)
        assert detail and "compiles" in detail

    def test_rules_from_spec_and_defaults(self):
        from paddle_tpu.observability.watchdog import (
            CompileStormRule, MfuDriftRule, default_rules,
            rules_from_spec)
        rules = rules_from_spec(
            "mfu_drift:factor=0.5;compile_storm:max_delta=10")
        assert isinstance(rules[0], MfuDriftRule)
        assert rules[0].factor == 0.5
        assert isinstance(rules[1], CompileStormRule)
        assert rules[1].max_delta == 10
        kinds = {type(r) for r in default_rules()}
        assert {MfuDriftRule, CompileStormRule} <= kinds

    def test_watchdog_fires_mfu_alert_end_to_end(self):
        from paddle_tpu.observability.recorder import FlightRecorder
        from paddle_tpu.observability.watchdog import (MfuDriftRule,
                                                       Watchdog)
        reg = MetricsRegistry()
        g = reg.gauge("paddle_tpu_train_mfu", "")
        wd = Watchdog(rules=[MfuDriftRule(factor=0.8)], registry=reg,
                      recorder=FlightRecorder(capacity=16), cooldown=0.0)
        g.set(0.5)
        assert wd.evaluate_once(now=1.0) == []
        g.set(0.1)
        alerts = wd.evaluate_once(now=2.0)
        assert len(alerts) == 1 and alerts[0].rule == "mfu_drift"


# ------------------------------------------------------------- bench compare
class TestBenchCompare:
    def test_flags_value_regression(self):
        import bench
        cur = {"value": 0.40, "detail": {"step_time_s": 0.30}}
        prev = {"value": 0.50, "detail": {"step_time_s": 0.30}}
        regs = bench.compare_records(cur, prev, tolerance=0.05)
        assert len(regs) == 1 and "value" in regs[0]

    def test_flags_step_time_regression(self):
        import bench
        cur = {"value": 0.50, "detail": {"step_time_s": 0.40}}
        prev = {"value": 0.50, "detail": {"step_time_s": 0.30}}
        regs = bench.compare_records(cur, prev, tolerance=0.05)
        assert len(regs) == 1 and "step_time_s" in regs[0]

    def test_within_tolerance_ok(self):
        import bench
        cur = {"value": 0.49, "detail": {"step_time_s": 0.305}}
        prev = {"value": 0.50, "detail": {"step_time_s": 0.30}}
        assert bench.compare_records(cur, prev, tolerance=0.05) == []

    def test_prev_record_reads_artifacts(self):
        import bench
        prev = bench._prev_record()
        # the repo ships BENCH_r01..r05; the newest parsed payload wins
        assert prev is not None and prev["value"] == pytest.approx(0.5148)
