"""ONNX export (VERDICT r3 Missing #8; reference
python/paddle/onnx/export.py).

The exporter maps the traced jaxpr onto ONNX ops into a vendored subset
of the public schema.  Tests prove SEMANTIC parity, not just structure:
the written .onnx file is parsed back from disk and re-executed by an
independent numpy evaluator of the emitted op set, then compared against
the live model's outputs.
"""

import os

import numpy as np
import pytest

import paddle_tpu as pp
from paddle_tpu.jit.save_load import InputSpec


def _load_model(path):
    from paddle_tpu.onnx import onnx_mini_pb2 as pb
    m = pb.ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m


_NP_DTYPES = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
              10: np.float16, 11: np.float64}


def _tensor_to_np(t):
    dt = _NP_DTYPES[t.data_type]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dt).reshape(tuple(t.dims))
    raise AssertionError("initializers use raw_data")


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == 2:
            out[a.name] = int(a.i)
        elif a.type == 1:
            out[a.name] = float(a.f)
        elif a.type == 7:
            out[a.name] = list(a.ints)
        elif a.type == 3:
            out[a.name] = a.s.decode()
    return out


def _run_onnx(model, feeds):
    """Independent numpy evaluator for the exporter's op set."""
    import scipy.special  # erf
    env = dict(feeds)
    for init in model.graph.initializer:
        env[init.name] = _tensor_to_np(init)

    def conv(x, w, at):
        import jax.numpy as jnp
        from jax import lax
        pads = at["pads"]
        n = len(pads) // 2
        padding = list(zip(pads[:n], pads[n:]))
        return np.asarray(lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), at["strides"], padding,
            rhs_dilation=at["dilations"],
            feature_group_count=at.get("group", 1)))

    for node in model.graph.node:
        i = [env[n] for n in node.input]
        at = _attrs(node)
        op = node.op_type
        if op == "Add":
            r = i[0] + i[1]
        elif op == "Sub":
            r = i[0] - i[1]
        elif op == "Mul":
            r = i[0] * i[1]
        elif op == "Div":
            r = i[0] / i[1]
        elif op == "Max":
            r = np.maximum(i[0], i[1])
        elif op == "Min":
            r = np.minimum(i[0], i[1])
        elif op == "Pow":
            r = i[0] ** i[1]
        elif op == "Neg":
            r = -i[0]
        elif op == "Exp":
            r = np.exp(i[0])
        elif op == "Log":
            r = np.log(i[0])
        elif op == "Tanh":
            r = np.tanh(i[0])
        elif op == "Sigmoid":
            r = 1 / (1 + np.exp(-i[0]))
        elif op == "Sqrt":
            r = np.sqrt(i[0])
        elif op == "Reciprocal":
            r = 1.0 / i[0]
        elif op == "Abs":
            r = np.abs(i[0])
        elif op == "Erf":
            r = scipy.special.erf(i[0])
        elif op == "Identity":
            r = i[0]
        elif op == "Cast":
            r = i[0].astype(_NP_DTYPES[at["to"]])
        elif op == "Reshape":
            r = i[0].reshape(tuple(int(v) for v in i[1]))
        elif op == "Expand":
            r = np.broadcast_to(i[0], tuple(int(v) for v in i[1]))
        elif op == "Transpose":
            r = np.transpose(i[0], at["perm"])
        elif op == "MatMul":
            r = np.matmul(i[0], i[1])
        elif op == "Conv":
            r = conv(i[0], i[1], at)
        elif op == "ReduceSum":
            r = i[0].sum(axis=tuple(int(v) for v in i[1]),
                         keepdims=bool(at.get("keepdims", 1)))
        elif op == "ReduceMean":
            r = i[0].mean(axis=tuple(at["axes"]),
                          keepdims=bool(at.get("keepdims", 1)))
        elif op == "ReduceMax":
            r = i[0].max(axis=tuple(at["axes"]),
                         keepdims=bool(at.get("keepdims", 1)))
        elif op == "Where":
            r = np.where(i[0], i[1], i[2])
        elif op == "Less":
            r = i[0] < i[1]
        elif op == "LessOrEqual":
            r = i[0] <= i[1]
        elif op == "Greater":
            r = i[0] > i[1]
        elif op == "GreaterOrEqual":
            r = i[0] >= i[1]
        elif op == "Equal":
            r = i[0] == i[1]
        elif op == "Not":
            r = ~i[0]
        elif op == "And":
            r = i[0] & i[1]
        elif op == "Or":
            r = i[0] | i[1]
        elif op == "Clip":
            r = np.clip(i[0], i[1], i[2])
        elif op == "Gather":
            r = np.take(i[0], i[1], axis=at.get("axis", 0))
        elif op == "Split":
            sizes = [int(v) for v in i[1]]
            idx = np.cumsum(sizes)[:-1]
            r = tuple(np.split(i[0], idx, axis=at.get("axis", 0)))
        elif op == "Concat":
            r = np.concatenate(i, axis=at["axis"])
        elif op == "Slice":
            starts = [int(v) for v in i[1]]
            ends = [int(v) for v in i[2]]
            axes = [int(v) for v in i[3]]
            steps = [int(v) for v in i[4]]
            sl = [slice(None)] * i[0].ndim
            for s, e, a, st in zip(starts, ends, axes, steps):
                sl[a] = slice(s, e, st)
            r = i[0][tuple(sl)]
        elif op == "Unsqueeze":
            r = i[0]
            for a in sorted(int(v) for v in i[1]):
                r = np.expand_dims(r, a)
        elif op == "Squeeze":
            r = np.squeeze(i[0], axis=tuple(int(v) for v in i[1]))
        else:
            raise AssertionError(f"evaluator: unexpected op {op}")
        if isinstance(r, tuple):
            for o, v in zip(node.output, r):
                env[o] = v
        else:
            env[node.output[0]] = r
    return [env[o.name] for o in model.graph.output]


class TestOnnxExport:
    def test_mlp_semantic_parity(self, tmp_path):
        pp.seed(0)
        net = pp.nn.Sequential(
            pp.nn.Linear(8, 16), pp.nn.ReLU(),
            pp.nn.Linear(16, 16), pp.nn.GELU(),
            pp.nn.Linear(16, 4), pp.nn.Softmax(axis=-1))
        x = np.random.default_rng(0).normal(size=(3, 8)).astype(np.float32)
        want = np.asarray(net(pp.to_tensor(x))._data)

        path = pp.onnx.export(net, str(tmp_path / "mlp"),
                              input_spec=[InputSpec([3, 8], "float32")])
        assert path.endswith(".onnx") and os.path.exists(path)
        model = _load_model(path)
        assert model.producer_name == "paddle_tpu"
        assert model.opset_import[0].version == 13
        (got,) = _run_onnx(model, {"input_0": x})
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_conv_net_semantic_parity(self, tmp_path):
        pp.seed(0)
        net = pp.nn.Sequential(
            pp.nn.Conv2D(3, 8, 3, padding=1), pp.nn.ReLU(),
            pp.nn.Conv2D(8, 4, 3, stride=2, padding=1), pp.nn.Tanh(),
            pp.nn.Flatten(), pp.nn.Linear(4 * 4 * 4, 5))
        x = np.random.default_rng(1).normal(
            size=(2, 3, 8, 8)).astype(np.float32)
        want = np.asarray(net(pp.to_tensor(x))._data)
        path = pp.onnx.export(net, str(tmp_path / "conv"),
                              input_spec=[InputSpec([2, 3, 8, 8],
                                                    "float32")])
        model = _load_model(path)
        assert any(n.op_type == "Conv" for n in model.graph.node)
        (got,) = _run_onnx(model, {"input_0": x})
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_layernorm_model(self, tmp_path):
        pp.seed(0)
        net = pp.nn.Sequential(pp.nn.Linear(6, 6), pp.nn.LayerNorm(6))
        x = np.random.default_rng(2).normal(size=(4, 6)).astype(np.float32)
        want = np.asarray(net(pp.to_tensor(x))._data)
        path = pp.onnx.export(net, str(tmp_path / "ln"),
                              input_spec=[InputSpec([4, 6], "float32")])
        (got,) = _run_onnx(_load_model(path), {"input_0": x})
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_embedding_classifier_parity(self, tmp_path):
        """Embedding lookup (gather + index clamp) exports and matches."""
        pp.seed(0)
        net = pp.nn.Sequential(pp.nn.Embedding(12, 8), pp.nn.Flatten(),
                               pp.nn.Linear(4 * 8, 3))
        ids = np.random.default_rng(3).integers(0, 12, (2, 4)) \
            .astype(np.int32)
        want = np.asarray(net(pp.to_tensor(ids))._data)
        path = pp.onnx.export(net, str(tmp_path / "emb"),
                              input_spec=[InputSpec([2, 4], "int32")])
        model = _load_model(path)
        assert any(n.op_type == "Gather" for n in model.graph.node)
        (got,) = _run_onnx(model, {"input_0": ids})
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_unmapped_primitive_clear_error(self, tmp_path):
        class Odd(pp.nn.Layer):
            def forward(self, x):
                from paddle_tpu.ops import math as m
                return m.cumsum(x, axis=0)  # cumsum is not mapped

        with pytest.raises(NotImplementedError, match="unmapped primitive"):
            pp.onnx.export(Odd(), str(tmp_path / "odd"),
                           input_spec=[InputSpec([3, 3], "float32")])

    def test_requires_input_spec(self, tmp_path):
        with pytest.raises(ValueError, match="input_spec"):
            pp.onnx.export(pp.nn.Linear(2, 2), str(tmp_path / "x"))


class TestTransformerExport:
    """Transformer encoder export (VERDICT r4 Missing #3 / Next #7):
    attention dot_general layouts, softmax, LayerNorm, gelu, embedding
    lookups — a full ErnieModel forward round-trips through the .onnx
    file and the independent evaluator."""

    def test_ernie_encoder_parity(self, tmp_path):
        from paddle_tpu.models.ernie import ErnieModel, ErnieConfig
        import paddle_tpu.onnx as onnx

        pp.seed(0)
        model = ErnieModel(ErnieConfig.tiny())
        model.eval()
        path = onnx.export(model, str(tmp_path / "ernie"),
                           input_spec=[InputSpec([2, 16], "int64")])
        m = _load_model(path)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, (2, 16)).astype(np.int64)
        # the graph input was declared by the tracer; feed by position
        feeds = {m.graph.input[0].name: ids}
        got = _run_onnx(m, feeds)[0]
        out = model(pp.to_tensor(ids.astype("int64")))
        if isinstance(out, tuple):
            out = out[0]
        want = np.asarray(out.numpy())
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_ernie_classifier_parity(self, tmp_path):
        from paddle_tpu.models.ernie import (ErnieConfig,
                                             ErnieForSequenceClassification)
        import paddle_tpu.onnx as onnx

        pp.seed(1)
        model = ErnieForSequenceClassification(ErnieConfig.tiny(),
                                               num_classes=3)
        model.eval()
        path = onnx.export(model, str(tmp_path / "ernie_cls"),
                           input_spec=[InputSpec([2, 12], "int64")])
        m = _load_model(path)
        rng = np.random.default_rng(1)
        ids = rng.integers(0, 128, (2, 12)).astype(np.int64)
        got = _run_onnx(m, {m.graph.input[0].name: ids})[0]
        want = np.asarray(model(pp.to_tensor(ids.astype("int64"))).numpy())
        assert got.shape == (2, 3)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_attention_dot_general_layouts(self, tmp_path):
        """The canonicalized general dot_general: raw q@k^T / probs@v
        with (batch, head) batch dims, exported and re-evaluated."""
        import jax.numpy as jnp
        import paddle_tpu.onnx as onnx
        from paddle_tpu.core.dispatch import unwrap

        class RawAttn(pp.nn.Layer):
            def forward(self, q, k, v):
                qd, kd, vd = (unwrap(t) for t in (q, k, v))
                s = jnp.einsum("bhqd,bhkd->bhqk", qd, kd)
                import jax
                p = jax.nn.softmax(s / qd.shape[-1] ** 0.5, axis=-1)
                return jnp.einsum("bhqk,bhkd->bhqd", p, vd)

        pp.seed(2)
        model = RawAttn()
        path = onnx.export(
            model, str(tmp_path / "rawattn"),
            input_spec=[InputSpec([2, 3, 5, 4], "float32")] * 3)
        m = _load_model(path)
        rng = np.random.default_rng(2)
        q, k, v = (rng.normal(size=(2, 3, 5, 4)).astype(np.float32)
                   for _ in range(3))
        names = [vi.name for vi in m.graph.input]
        got = _run_onnx(m, dict(zip(names, (q, k, v))))[0]
        want = np.asarray(unwrap(model(pp.to_tensor(q), pp.to_tensor(k),
                                       pp.to_tensor(v))))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
